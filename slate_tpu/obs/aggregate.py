"""Pod-scale telemetry aggregation: N processes, one fleet view.

The reference merges per-rank trace buffers and counter payloads
post-hoc (each MPI rank writes its own Trace/counter stream; rank 0
stitches the SVG and sums the counters). Our serving analog: every
process exports a Metrics snapshot, flop/bytes ledger snapshots, and a
Chrome trace; this module merges them —

* **counters summed exactly** (plain float addition — merging two
  copies of the same snapshot doubles every counter bit-exactly,
  which is the aggregation acceptance test);
* **histograms merged**: counts and sums add, min/max take the
  extremes, the merged mean is recomputed, and the merged p50/p99 are
  the count-weighted mean of the per-process quantiles (an
  approximation — exact fleet quantiles need the raw samples, which
  snapshots deliberately do not ship; documented in PERF.md Round 12);
  the worst-valued exemplar survives;
* **gauges labeled per host** (a fleet has one resident_bytes per
  chip, not one sum; summable gauges are ALSO aggregated under
  ``fleet_*`` names so capacity totals stay one query);
* **derived headline rates recomputed** from the merged counters with
  the same formulas ``runtime.Metrics._derive`` uses (pinned equal by
  test — this module cannot import the runtime without dragging jax
  into the obs layer, so the formulas are mirrored, not shared);
* **traces combined keyed by trace-id** (obs.merge.
  ``combine_process_traces``): per-process pid namespaces, span
  identities prefixed with the host label so two processes' span id
  counters cannot collide in one Perfetto load.

Everything here is pure snapshot-in/snapshot-out (stdlib-only,
jax-free): the processes can be 8 hosts of a pod or one host's
bench + serve jobs — aggregation is the same fold either way.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

from .merge import combine_process_traces  # re-export (fleet surface)

__all__ = [
    "combine_process_traces", "merge_attribution_snapshots",
    "merge_bytes_snapshots",
    "merge_flop_snapshots", "merge_histograms",
    "merge_incident_payloads", "merge_journal_payloads",
    "merge_metrics_snapshots", "merge_placement_snapshots",
    "merge_quota_payloads", "merge_timeseries_payloads",
    "aggregate_processes", "placement_from_checkpoint",
    "render_fleet_prometheus", "write_fleet",
]

# gauges that are meaningfully summable across processes (capacity
# totals); everything else (headroom, per-chip charges, burn rates)
# only makes sense per host
_SUMMABLE_GAUGES = ("resident_bytes_total", "resident_bytes",
                    "peak_hbm_bytes", "queue_depth", "inflight_batches")


def _hosts(n: int, hosts: Optional[Sequence[str]]) -> List[str]:
    if hosts is None:
        return [f"proc{i}" for i in range(n)]
    if len(hosts) != n:
        raise ValueError(f"{n} snapshots but {len(hosts)} host labels")
    return list(hosts)


def merge_histograms(snaps: Sequence[dict]) -> dict:
    """Merge per-process Histogram.snapshot() dicts (module
    docstring); empty input -> empty-histogram shape."""
    count = sum(int(s.get("count", 0)) for s in snaps)
    total = sum(float(s.get("sum", 0.0)) for s in snaps)
    mins = [s["min"] for s in snaps if s.get("min") is not None]
    maxs = [s["max"] for s in snaps if s.get("max") is not None]
    out = {
        "count": count,
        "sum": total,
        "min": min(mins) if mins else None,
        "max": max(maxs) if maxs else None,
        "mean": (total / count) if count else None,
    }
    for q in ("p50", "p99"):
        num = den = 0.0
        for s in snaps:
            c = int(s.get("count", 0))
            if c and s.get(q) is not None:
                num += c * float(s[q])
                den += c
        out[q] = (num / den) if den else 0.0
    exemplars = [s.get("exemplar") for s in snaps if s.get("exemplar")]
    out["exemplar"] = (max(exemplars, key=lambda e: e.get("value", 0.0))
                       if exemplars else None)
    return out


def _derive(counters: dict, hists: dict) -> dict:
    """Mirror of runtime.Metrics._derive over MERGED counters (see
    module docstring for why it is mirrored, and the pin test)."""
    hits = counters.get("cache_hits", 0.0)
    misses = counters.get("cache_misses", 0.0)
    total = hits + misses
    solve_seconds = hists.get("solve_latency", {}).get("sum", 0.0)
    solves = counters.get("solves_total", 0.0)
    flops = counters.get("solve_flops_total", 0.0)
    return {
        "cache_hit_rate": hits / total if total else 0.0,
        "solves_per_sec": (solves / solve_seconds
                           if solve_seconds > 0 else 0.0),
        "gflops": (flops / solve_seconds / 1e9
                   if solve_seconds > 0 else 0.0),
    }


def merge_metrics_snapshots(snaps: Sequence[dict],
                            hosts: Optional[Sequence[str]] = None) -> dict:
    """N ``Metrics.snapshot()`` dicts -> one fleet snapshot (module
    docstring). The result renders through
    ``exposition.render_prometheus`` unchanged; per-host gauges ride in
    ``gauges_per_host`` (``render_fleet_prometheus`` emits them with
    ``host=`` labels)."""
    snaps = list(snaps)
    labels = _hosts(len(snaps), hosts)
    counters: Dict[str, float] = {}
    for s in snaps:
        for k, v in s.get("counters", {}).items():
            counters[k] = counters.get(k, 0.0) + v
    hist_names = sorted({k for s in snaps for k in s.get("histograms", {})})
    hists = {name: merge_histograms(
        [s["histograms"][name] for s in snaps
         if name in s.get("histograms", {})]) for name in hist_names}
    gauges_per_host = {label: dict(s.get("gauges", {}))
                       for label, s in zip(labels, snaps)}
    # round 23: the per-host gauge rows keep their host label AND
    # their set-time stamps — a fleet reader can tell a fresh value
    # from one last true minutes before the scrape
    gauge_ts_per_host = {label: dict(s.get("gauge_ts", {}))
                         for label, s in zip(labels, snaps)}
    fleet_gauges = {}
    for g in _SUMMABLE_GAUGES:
        vals = [s["gauges"][g] for s in snaps if g in s.get("gauges", {})]
        if vals:
            fleet_gauges[f"fleet_{g}"] = sum(vals)
    return {
        "hosts": labels,
        "processes": len(snaps),
        "uptime_s": max((s.get("uptime_s", 0.0) for s in snaps),
                        default=0.0),
        "counters": counters,
        "histograms": hists,
        "gauges": fleet_gauges,
        "gauges_per_host": gauges_per_host,
        "gauge_ts_per_host": gauge_ts_per_host,
        "derived": _derive(counters, hists),
    }


def _merge_keyed_sums(snaps: Sequence[dict], key: str) -> Dict[str, dict]:
    """Union per-op/per-kind tables, summing every numeric field."""
    out: Dict[str, dict] = {}
    for s in snaps:
        for op, row in s.get(key, {}).items():
            dst = out.setdefault(op, {})
            if isinstance(row, dict):
                for k, v in row.items():
                    dst[k] = dst.get(k, 0) + v
            else:  # flop ledger per_op: bare floats
                dst["value"] = dst.get("value", 0.0) + row
    return out


def merge_flop_snapshots(snaps: Sequence[dict]) -> dict:
    """N ``FlopLedger.snapshot()`` dicts -> one (totals/per-op/calls
    summed)."""
    out = {"flops_total": sum(s.get("flops_total", 0.0) for s in snaps),
           "per_op": {}, "calls": {}}
    for s in snaps:
        for op, v in s.get("per_op", {}).items():
            out["per_op"][op] = out["per_op"].get(op, 0.0) + v
        for op, c in s.get("calls", {}).items():
            out["calls"][op] = out["calls"].get(op, 0) + c
    return out


def merge_bytes_snapshots(snaps: Sequence[dict]) -> dict:
    """N ``BytesLedger.snapshot()`` dicts -> one."""
    return {
        "bytes_total": sum(s.get("bytes_total", 0.0) for s in snaps),
        "collective_bytes_total": sum(
            s.get("collective_bytes_total", 0.0) for s in snaps),
        "per_op": _merge_keyed_sums(snaps, "per_op"),
        "per_collective": _merge_keyed_sums(snaps, "per_collective"),
    }


def merge_attribution_snapshots(snaps: Sequence[dict]) -> dict:
    """N ``AttributionLedger.snapshot()`` dicts -> one fleet
    attribution view: per-(tenant, handle) cells summed per counter
    class, tenant and global totals recomputed from the merged cells
    (sorted order). Every increment lives on the dyadic grid
    (obs/attribution.py), so these sums are exact and the fleet's
    per-tenant rows still sum bit-exactly to the fleet's folded
    global counters — the conservation invariant survives the fold,
    including under a round-14 ``snapshot_drop`` (a dropped process
    loses its metrics AND attribution snapshots together, so both
    sides of the invariant shrink consistently). ``heat`` is summed
    across processes (a replicated handle's fleet heat is its total
    access rate — the replication signal); ``last_access`` takes the
    newest.

    **Partial hosts (round 17):** ``None`` entries — a host inside the
    crash window whose live attribution snapshot is gone while its
    checkpoint survives — are tolerated: they are skipped (their cells
    died with the process, exactly like their global counters did, so
    conservation over the SURVIVING snapshots still holds) and counted
    in ``partial_processes``. Before this, only the all-or-nothing
    ``snapshot_drop`` case (both sides absent) was pinned."""
    raw = list(snaps)  # a generator must not be consumed before the
    snaps = [s for s in raw if s]  # "processes" count below
    partial = len(raw) - len(snaps)
    tenants: Dict[str, dict] = {}
    halflife = None
    for s in snaps:
        if halflife is None:
            halflife = s.get("halflife_s")
        for tenant, trow in s.get("tenants", {}).items():
            dst = tenants.setdefault(tenant, {"totals": {},
                                              "handles": {}})
            for h, hrow in trow.get("handles", {}).items():
                cell = dst["handles"].setdefault(h, {})
                for cls, v in hrow.items():
                    if cls == "last_access":
                        prev = cell.get("last_access")
                        if v is not None and (prev is None or v > prev):
                            cell["last_access"] = v
                    else:
                        cell[cls] = cell.get(cls, 0.0) + v
    totals: Dict[str, float] = {}
    for tenant in sorted(tenants):
        trow = tenants[tenant]
        for h in sorted(trow["handles"]):
            for cls, v in trow["handles"][h].items():
                if cls in ("last_access", "heat"):
                    continue
                trow["totals"][cls] = trow["totals"].get(cls, 0.0) + v
                totals[cls] = totals.get(cls, 0.0) + v
    return {
        "schema": "slate_tpu.attribution.v1",
        "fleet": True,
        "processes": len(snaps),
        "partial_processes": partial,
        "halflife_s": halflife,
        "tenants": tenants,
        "totals": totals,
    }


def placement_from_checkpoint(manifest: dict,
                              host: Optional[str] = None) -> dict:
    """A checkpoint manifest (runtime/checkpoint.py,
    ``slate_tpu.checkpoint.v1``) -> a placement-snapshot-SHAPED doc for
    the fleet fold: the crash-window bridge. When a process dies its
    live ``placement_snapshot()`` is gone, but its last checkpoint
    records the same per-resident rows (op/n/dtype/bytes/heat/health),
    so the fold need not go blind on that host — the derived doc is
    marked ``"partial": True`` and ``merge_placement_snapshots``
    surfaces it under ``partial_hosts``. ``bytes_per_chip`` for a mesh
    resident is the checkpoint's TOTAL gathered bytes (the checkpoint
    is placement-independent); live rows stay the per-chip truth."""
    host = host or str(manifest.get("host", "checkpoint"))
    rows = []
    for rec in manifest.get("records", []):
        if not isinstance(rec, dict):
            continue
        payload_bytes = _node_nbytes(rec.get("payload"))
        health = rec.get("health") or {}
        hrep = (repr(str(rec.get("handle")))
                if rec.get("handle_type") == "str"
                else str(rec.get("handle")))
        rows.append({
            "host": host,
            "tenant": str(rec.get("tenant") or "default"),
            "handle": hrep,
            "op": str(rec.get("op", "")),
            "n": int(rec.get("n", 0)),
            "dtype": str(rec.get("dtype", "")),
            "bytes_per_chip": int(payload_bytes),
            "heat": float(rec.get("heat") or 0.0),
            "last_access": rec.get("last_access"),
            "health": health.get("state"),
            "condest": health.get("condest"),
            "growth": health.get("growth"),
        })
    return {
        "schema": "slate_tpu.placement_snapshot.v2",
        "host": host,
        "generated_at": manifest.get("generated_at"),
        "partial": True,
        "rows": rows,
    }


def _node_nbytes(desc) -> int:
    """Total blob bytes under one checkpoint node descriptor (pure
    manifest walk — this module is stdlib-only, so the byte count
    comes from the recorded ``nbytes`` fields, not numpy)."""
    if not isinstance(desc, dict):
        return 0
    if desc.get("type") == "tuple":
        return sum(_node_nbytes(d) for d in desc.get("items", []))
    total = 0
    for v in desc.values():
        if isinstance(v, dict) and "nbytes" in v:
            total += int(v.get("nbytes", 0) or 0)
    return total


def merge_placement_snapshots(docs: Sequence[dict]) -> dict:
    """N ``Session.placement_snapshot()`` documents -> the fleet
    placement input (ROADMAP item 1): every host's resident rows in
    one row set (each row already carries its host label) plus a
    per-tenant rollup — resident bytes, total heat, handle count per
    tenant across the fleet — the numbers a quota/placement policy
    reads first. Rows sort by (tenant, heat desc) so the hottest
    handles lead each tenant's slice."""
    docs = [d for d in docs if d]  # round 17: tolerate absent hosts
    rows = []
    hosts = []
    partial_hosts = []
    for doc in docs:
        h = doc.get("host", f"proc{len(hosts)}")
        hosts.append(h)
        if doc.get("partial"):
            # round 17: a checkpoint-derived doc for a host inside the
            # crash window (live snapshot gone, checkpoint survives) —
            # its rows join the fold, labeled so a placement policy
            # can discount their staleness
            partial_hosts.append(h)
        rows.extend(dict(r) for r in doc.get("rows", []))
    rows.sort(key=lambda r: (str(r.get("tenant", "")),
                             -float(r.get("heat", 0.0) or 0.0),
                             str(r.get("handle", ""))))
    per_tenant: Dict[str, dict] = {}
    for r in rows:
        t = per_tenant.setdefault(str(r.get("tenant", "")), {
            "resident_bytes": 0.0, "heat": 0.0, "handles": 0,
            "suspect_handles": 0, "hosts": set()})
        t["resident_bytes"] += float(r.get("bytes_per_chip", 0.0) or 0.0)
        t["heat"] += float(r.get("heat", 0.0) or 0.0)
        t["handles"] += 1
        if r.get("health") == "suspect":
            # round 16: health-aware placement — a suspect resident is
            # never a replication candidate however hot it runs
            t["suspect_handles"] += 1
        t["hosts"].add(str(r.get("host", "")))
    for t in per_tenant.values():
        t["hosts"] = sorted(t["hosts"])
    return {
        "schema": "slate_tpu.fleet_placement.v1",
        "hosts": hosts,
        "processes": len(docs),
        "partial_hosts": partial_hosts,
        "rows": rows,
        "per_tenant": per_tenant,
    }


def merge_quota_payloads(snaps: Sequence[dict]) -> dict:
    """N ``Session.quotas_payload()`` dicts -> one fleet quota view
    (round 18): per-tenant resident bytes/counts summed across hosts
    (the fleet-wide share a capacity planner bills against) and the
    quota counters folded — ``None``/disabled entries tolerated (a
    host without a tenant table simply contributes nothing, the
    partial-host discipline)."""
    snaps = [s for s in snaps if s and s.get("enabled")]
    tenants: Dict[str, dict] = {}
    counters: Dict[str, float] = {}
    for s in snaps:
        for t, row in s.get("tenants", {}).items():
            dst = tenants.setdefault(t, {"resident_bytes": 0.0,
                                         "residents": 0,
                                         "max_resident_bytes": None})
            dst["resident_bytes"] += float(row.get("resident_bytes",
                                                   0.0) or 0.0)
            dst["residents"] += int(row.get("residents", 0) or 0)
            sub = row.get("max_resident_bytes")
            if sub is not None:
                # the fleet-wide sub-budget is the per-host budget
                # summed (each host enforces its own share)
                dst["max_resident_bytes"] = (
                    (dst["max_resident_bytes"] or 0) + sub)
        for k, v in s.get("counters", {}).items():
            counters[k] = counters.get(k, 0.0) + v
    return {
        "enabled": bool(snaps),
        "processes": len(snaps),
        "tenants": tenants,
        "counters": counters,
    }


def merge_journal_payloads(payloads: Sequence[dict],
                           hosts: Optional[Sequence[str]] = None
                           ) -> dict:
    """N ``DecisionJournal.payload()`` docs -> one fleet decision
    timeline (round 22): every ring event host-labeled and merged
    into ONE ts-ordered stream, per-kind / per-(kind, outcome) counts
    summed exactly (the conservation invariant: fleet count(kind) ==
    sum of per-process counts — merging two copies of one journal
    doubles every count bit-exactly, same as the metrics fold)."""
    labels = _hosts(len(payloads), hosts)
    events: List[dict] = []
    counts: Dict[str, float] = {}
    outcome_counts: Dict[str, float] = {}
    recorded = dropped = 0
    for label, p in zip(labels, payloads):
        if not p:
            continue
        for ev in p.get("events", ()):
            row = dict(ev)
            row["host"] = label
            events.append(row)
        for k, v in p.get("counts", {}).items():
            counts[k] = counts.get(k, 0.0) + v
        for k, v in p.get("outcome_counts", {}).items():
            outcome_counts[k] = outcome_counts.get(k, 0.0) + v
        recorded += int(p.get("recorded", 0))
        dropped += int(p.get("dropped", 0))
    # ts-ordered; (host, seq) breaks clock ties deterministically
    events.sort(key=lambda e: (e.get("ts", 0.0), e.get("host", ""),
                               e.get("seq", 0)))
    return {
        "schema": "slate_tpu.journal.fleet.v1",
        "processes": len(payloads),
        "hosts": labels,
        "recorded": recorded,
        "dropped": dropped,
        "counts": counts,
        "outcome_counts": outcome_counts,
        "events": events,
    }


def merge_timeseries_payloads(payloads: Sequence[dict],
                              hosts: Optional[Sequence[str]] = None
                              ) -> dict:
    """N ``TimeseriesStore.payload()`` docs -> one fleet history view
    (round 23): every member's series kept host-labeled under
    ``"<host>:<name>"`` (a fleet has one queue-depth history per
    member, not one mush), drop accounting summed, and every COUNTER
    series' lifetime sum folded into ``counter_totals`` by plain float
    addition — the round-12 conservation discipline: merging two
    copies of one payload doubles every counter total bit-exactly,
    and the fleet total equals the sum of the members' cumulative
    counters. ``None`` entries (a host inside the crash window)
    are tolerated and counted ``partial_processes``."""
    raw = list(payloads)
    labels = _hosts(len(raw), hosts)
    series: Dict[str, dict] = {}
    counter_totals: Dict[str, float] = {}
    dropped_series = dropped_samples = 0
    partial = 0
    for label, p in zip(labels, raw):
        if not p:
            partial += 1
            continue
        for name, row in p.get("series", {}).items():
            labeled = dict(row)
            labeled["host"] = label
            series[f"{label}:{name}"] = labeled
            if row.get("kind") == "counter":
                counter_totals[name] = (counter_totals.get(name, 0.0)
                                        + float(row.get("total_sum",
                                                        0.0)))
        dropped_series += int(p.get("dropped_series", 0))
        dropped_samples += int(p.get("dropped_samples", 0))
    return {
        "schema": "slate_tpu.timeseries.fleet.v1",
        "processes": len(raw),
        "partial_processes": partial,
        "hosts": labels,
        "dropped_series": dropped_series,
        "dropped_samples": dropped_samples,
        "series": series,
        "counter_totals": counter_totals,
    }


def merge_incident_payloads(payloads: Sequence[dict],
                            hosts: Optional[Sequence[str]] = None
                            ) -> dict:
    """N ``IncidentCapture.payload()`` docs -> one fleet incident
    timeline: every incident labeled with its process host (the
    document's own ``host`` field is preserved — the label records
    which FOLD slot it came from), ts-ordered, capture totals
    summed."""
    labels = _hosts(len(payloads), hosts)
    incidents: List[dict] = []
    captured = 0
    for label, p in zip(labels, payloads):
        if not p:
            continue
        for doc in p.get("incidents", ()):
            row = dict(doc)
            row["fold_host"] = label
            incidents.append(row)
        captured += int(p.get("captured", 0))
    incidents.sort(key=lambda d: (d.get("ts", 0.0),
                                  d.get("fold_host", ""),
                                  d.get("id", "")))
    return {
        "schema": "slate_tpu.incidents.fleet.v1",
        "processes": len(payloads),
        "hosts": labels,
        "captured": captured,
        "incidents": incidents,
    }


def aggregate_processes(metric_snaps: Sequence[dict],
                        flop_snaps: Optional[Sequence[dict]] = None,
                        bytes_snaps: Optional[Sequence[dict]] = None,
                        hosts: Optional[Sequence[str]] = None,
                        attribution_snaps: Optional[Sequence[dict]] = None,
                        placement_docs: Optional[Sequence[dict]] = None,
                        quota_payloads: Optional[Sequence[dict]] = None
                        ) -> dict:
    """One fleet document: merged metrics (+ ledgers, tenant
    attribution, placement snapshots, and quota payloads when
    given)."""
    doc = {"fleet": True,
           "metrics": merge_metrics_snapshots(metric_snaps, hosts)}
    if flop_snaps is not None:
        doc["flops"] = merge_flop_snapshots(flop_snaps)
    if bytes_snaps is not None:
        doc["bytes"] = merge_bytes_snapshots(bytes_snaps)
    if attribution_snaps is not None:
        doc["attribution"] = merge_attribution_snapshots(attribution_snaps)
    if placement_docs is not None:
        doc["placement"] = merge_placement_snapshots(placement_docs)
    if quota_payloads is not None:
        doc["quotas"] = merge_quota_payloads(quota_payloads)
    return doc


def render_fleet_prometheus(fleet: dict, prefix: str = "slate_tpu") -> str:
    """Prometheus text of an ``aggregate_processes`` document: the
    merged counters/histograms/derived through the standard renderer
    (process-local ledger sections disabled — the fleet ledgers are
    rendered from the MERGED snapshots below), then per-host gauges
    with ``host=`` labels."""
    from .exposition import _num, _san, render_prometheus
    merged = fleet["metrics"]
    text = render_prometheus(merged, prefix=prefix, ledger=False,
                             bytes_ledger=False)
    lines = [text.rstrip("\n")]
    for host in merged["hosts"]:
        gauges = merged["gauges_per_host"].get(host, {})
        for k in sorted(gauges):
            name = f"{prefix}_{_san(k)}"
            lines.append(f'{name}{{host="{_san(host)}"}} '
                         f"{_num(gauges[k])}")
    if "flops" in fleet:
        lines.append(f"# TYPE {prefix}_fleet_driver_flops_total counter")
        lines.append(f"{prefix}_fleet_driver_flops_total "
                     f"{_num(fleet['flops']['flops_total'])}")
    if "bytes" in fleet:
        lines.append(f"# TYPE {prefix}_fleet_driver_bytes_total counter")
        lines.append(f"{prefix}_fleet_driver_bytes_total "
                     f"{_num(fleet['bytes']['bytes_total'])}")
        lines.append(
            f"# TYPE {prefix}_fleet_collective_bytes_total counter")
        lines.append(f"{prefix}_fleet_collective_bytes_total "
                     f"{_num(fleet['bytes']['collective_bytes_total'])}")
    if "attribution" in fleet:
        # round 15: the fleet's per-tenant rollup, through the SAME
        # renderer the single-process /metrics route uses
        from .exposition import render_tenant_sections
        lines.extend(render_tenant_sections(fleet["attribution"],
                                            prefix=f"{prefix}_fleet"))
    if "placement" in fleet:
        lines.append(f"# TYPE {prefix}_fleet_tenant_resident_bytes gauge")
        lines.append(f"# TYPE {prefix}_fleet_tenant_heat gauge")
        pt = fleet["placement"].get("per_tenant", {})
        for tenant in sorted(pt):
            lines.append(
                f'{prefix}_fleet_tenant_resident_bytes'
                f'{{tenant="{_san(tenant)}"}} '
                f"{_num(pt[tenant]['resident_bytes'])}")
            lines.append(
                f'{prefix}_fleet_tenant_heat{{tenant="{_san(tenant)}"}} '
                f"{_num(pt[tenant]['heat'])}")
    if fleet.get("quotas", {}).get("enabled"):
        # round 18: the fleet quota rollup — per-tenant resident bytes
        # against the summed sub-budgets plus the folded quota
        # counters (rollups only; handle cardinality stays in JSON —
        # the round-15 discipline)
        q = fleet["quotas"]
        lines.append(
            f"# TYPE {prefix}_fleet_tenant_quota_resident_bytes gauge")
        for tenant in sorted(q.get("tenants", {})):
            row = q["tenants"][tenant]
            lines.append(
                f'{prefix}_fleet_tenant_quota_resident_bytes'
                f'{{tenant="{_san(tenant)}"}} '
                f"{_num(row['resident_bytes'])}")
            if row.get("max_resident_bytes") is not None:
                lines.append(
                    f'{prefix}_fleet_tenant_quota_max_resident_bytes'
                    f'{{tenant="{_san(tenant)}"}} '
                    f"{_num(row['max_resident_bytes'])}")
        for k in sorted(q.get("counters", {})):
            lines.append(f"# TYPE {prefix}_fleet_{_san(k)} counter")
            lines.append(
                f"{prefix}_fleet_{_san(k)} {_num(q['counters'][k])}")
    return "\n".join(lines) + "\n"


def write_fleet(fleet: dict, json_path: Optional[str] = None,
                prom_path: Optional[str] = None) -> dict:
    """Persist one fleet view (JSON and/or Prometheus text)."""
    if json_path is not None:
        with open(json_path, "w") as f:
            json.dump(fleet, f, indent=2, sort_keys=True)
            f.write("\n")
    if prom_path is not None:
        with open(prom_path, "w") as f:
            f.write(render_fleet_prometheus(fleet))
    return fleet
