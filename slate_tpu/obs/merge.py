"""Host/device trace merging + the measured lookahead-overlap metric.

``jax.profiler`` captures device timelines; exported through the
TensorBoard profile plugin (or ``trace_event`` conversion) they arrive
as Chrome-trace JSON whose event names carry our ``jax.named_scope``
labels — the per-level ``potrf_l{k}_tile/_panel/_trail_next/_trail_rest
/_l{k+1}_tile_lookahead`` (linalg/cholesky.py) and ``geqrf_l{k}_*``
(linalg/qr.py) scopes the round-7 pipeline plants. This module does two
things with them:

* :func:`lookahead_overlap` — the MEASURED version of the number
  PERF.md round 7 only models: for each level k, how much of the
  level-(k+1) lookahead panel's device time runs CONCURRENTLY with the
  level-k remainder ("trail_rest") gemms. ``overlap_fraction`` = hidden
  panel seconds / total lookahead-panel seconds: 1.0 means the panel
  chain is fully hidden (the per-level floor is max(panel, trailing)),
  0.0 means the schedule serialized (the floor degrades to their sum).

* :func:`merge_traces` — re-bases a device-trace event list into a host
  span export (pid 2, "device"), aligning the earliest device event to
  a named host anchor span, so one Perfetto load shows request → batch
  → factor host spans above the device lanes they dispatched.

Both work on any ``trace_event`` JSON (dict with ``traceEvents`` or a
bare list), gzipped or not — :func:`load_trace` /
:func:`find_device_traces` handle the profiler's output layout.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

from .export import DEVICE_PID

SCOPE_RE = re.compile(r"(potrf|getrf|geqrf)_l(\d+)_([a-zA-Z0-9_]+)")

Interval = Tuple[float, float]


def load_trace(path: str):
    """Load a trace_event JSON (optionally .gz); returns the event
    list."""
    if path.endswith(".gz"):
        with gzip.open(path, "rt", encoding="utf-8") as f:
            obj = json.load(f)
    else:
        with open(path, "r", encoding="utf-8") as f:
            obj = json.load(f)
    return events_of(obj)


def events_of(obj) -> List[dict]:
    if isinstance(obj, dict):
        return obj.get("traceEvents", [])
    return list(obj)


def find_device_traces(trace_dir: str) -> List[str]:
    """Chrome-format trace files under a ``jax.profiler.trace`` output
    directory (the TensorBoard plugin writes ``*.trace.json.gz``; some
    versions only emit ``.xplane.pb``, which needs the TensorBoard
    converter first — we return [] then and the caller reports
    'no chrome-format device trace found')."""
    hits: List[str] = []
    for pat in ("**/*.trace.json.gz", "**/*.trace.json"):
        hits.extend(glob.glob(os.path.join(trace_dir, pat), recursive=True))
    return sorted(hits)


# -- interval algebra --------------------------------------------------------


def _merge_intervals(ivs: List[Interval]) -> List[Interval]:
    out: List[Interval] = []
    for s, e in sorted(ivs):
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def _total(ivs: List[Interval]) -> float:
    return sum(e - s for s, e in ivs)


def _overlap(a: List[Interval], b: List[Interval]) -> float:
    """Total overlap seconds between two merged interval lists."""
    i = j = 0
    acc = 0.0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            acc += e - s
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return acc


def _scope_of(e: dict) -> Optional[re.Match]:
    """The named-scope match for one event, searched in the event name
    AND its string-valued args — backends differ on where the
    annotation survives (TPU xplane exports carry the scope path in
    args like ``tf_op``/``long_name``; XLA:CPU drops it entirely, in
    which case the caller honestly reports zero scoped levels)."""
    m = SCOPE_RE.search(e.get("name", ""))
    if m is not None:
        return m
    args = e.get("args")
    if isinstance(args, dict):
        for v in args.values():
            if isinstance(v, str):
                m = SCOPE_RE.search(v)
                if m is not None:
                    return m
    return None


def _scope_intervals(events: Iterable[dict], driver: str
                     ) -> Dict[Tuple[int, str], List[Interval]]:
    """(level, scope-kind) -> merged intervals (seconds) over all "X"
    events carrying a ``{driver}_l{k}_{kind}`` scope (in name or
    args)."""
    buckets: Dict[Tuple[int, str], List[Interval]] = {}
    for e in events:
        if e.get("ph") not in (None, "X"):
            continue
        dur = e.get("dur")
        ts = e.get("ts")
        if dur is None or ts is None:
            continue
        m = _scope_of(e)
        if m is None or m.group(1) != driver:
            continue
        level, kind = int(m.group(2)), m.group(3)
        buckets.setdefault((level, kind), []).append(
            (ts * 1e-6, (ts + dur) * 1e-6))
    return {k: _merge_intervals(v) for k, v in buckets.items()}


# -- the measured lookahead-overlap metric -----------------------------------

# scope kinds the lookahead pipeline factors EARLY (the work the
# schedule tries to hide) and the trailing remainder it hides them under
_LOOKAHEAD_KINDS = ("tile_lookahead", "panel_lookahead")
_REST_KIND = "trail_rest"


def lookahead_overlap(events: Iterable[dict], driver: str = "potrf") -> dict:
    """Measured lookahead overlap from a device trace (see module
    docstring). Returns per-level and aggregate numbers; all times in
    seconds. ``levels`` is empty when the trace carries no lookahead
    scopes (lookahead=0, or the backend stripped metadata)."""
    scoped = _scope_intervals(events, driver)
    levels: Dict[int, dict] = {}
    panel_s = hidden_s = 0.0
    for (level, kind), ivs in scoped.items():
        if kind not in _LOOKAHEAD_KINDS:
            continue
        rest = scoped.get((level - 1, _REST_KIND), [])
        p = _total(ivs)
        h = _overlap(ivs, rest)
        levels[level] = {
            "panel_s": p,
            "hidden_s": h,
            "hidden_fraction": h / p if p > 0 else 0.0,
        }
        panel_s += p
        hidden_s += h
    return {
        "driver": driver,
        "levels": {str(k): v for k, v in sorted(levels.items())},
        "panel_s": panel_s,
        "hidden_s": hidden_s,
        "overlap_fraction": hidden_s / panel_s if panel_s > 0 else 0.0,
    }


# -- multi-process combine (round 12: obs.aggregate's trace half) ------------

# pid namespace stride per process: every process emits pids 0 (host
# threads), 1 (phase lanes), 2 (re-based device lanes) — see
# obs.export; 100 leaves room for any future lane class
_PROC_PID_STRIDE = 100


def combine_process_traces(traces: Iterable, labels: Optional[List[str]]
                           = None) -> dict:
    """N processes' Chrome traces -> ONE trace, keyed by trace-id.

    The reference merges per-rank Trace buffers post-hoc; this is the
    trace_event version: process i's events keep their relative
    timestamps but move into a disjoint pid namespace
    (``pid + i * 100``), every event's args gain a ``host`` label, and
    span/trace identities are prefixed with it (two processes' span-id
    counters both start at 1 — unprefixed they would alias in one
    Perfetto load). Per-process ``process_name`` metadata is rewritten
    to ``{label}:{original}`` so the lanes stay attributable."""
    out: List[dict] = []
    for i, tr in enumerate(traces):
        label = (labels[i] if labels and i < len(labels) else f"proc{i}")
        base = i * _PROC_PID_STRIDE
        for e in events_of(tr):
            e = dict(e)
            e["pid"] = int(e.get("pid", 0)) + base
            args = dict(e.get("args") or {})
            if e.get("ph") == "M":
                if e.get("name") == "process_name" and "name" in args:
                    args["name"] = f"{label}:{args['name']}"
                e["args"] = args
                out.append(e)
                continue
            for key in ("trace_id", "span_id", "parent_id"):
                if args.get(key) is not None:
                    args[key] = f"{label}/{args[key]}"
            args["host"] = label
            e["args"] = args
            out.append(e)
    # the chrome validator (and readers) expect "X" events in ts order;
    # metadata first, as obs.export emits them
    out.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0)))
    return {"traceEvents": out, "displayTimeUnit": "ms"}


# -- host/device merge -------------------------------------------------------


def merge_traces(host_trace, device_events: Iterable[dict],
                 anchor: Optional[str] = None) -> dict:
    """One Chrome trace with the device lanes under the host spans.

    ``host_trace`` is a chrome_trace() dict (or event list); device
    events are re-based into pid ``DEVICE_PID`` with their earliest
    timestamp aligned to the start of the first host event named
    ``anchor`` (default: the earliest host event) — the coarse clock
    alignment the jax-profiler/host perf_counter pair allows without a
    shared timebase."""
    host = events_of(host_trace)
    dev = [dict(e) for e in events_of(device_events)
           if e.get("ph") in (None, "X", "M")]
    host_x = [e for e in host if e.get("ph") == "X"]
    anchor_ts = 0.0
    if host_x:
        anchored = [e for e in host_x if anchor and e.get("name") == anchor]
        anchor_ts = (anchored or host_x)[0]["ts"]
    dev_x = [e for e in dev if e.get("ph", "X") == "X"
             and e.get("ts") is not None]
    shift = anchor_ts - min((e["ts"] for e in dev_x), default=0.0)
    out = list(host)
    out.append({"ph": "M", "ts": 0, "pid": DEVICE_PID, "tid": 0,
                "name": "process_name", "args": {"name": "device"}})
    for e in dev:
        e["pid"] = DEVICE_PID
        if e.get("ts") is not None and e.get("ph", "X") == "X":
            e["ts"] = e["ts"] + shift
        e.setdefault("args", {})
        out.append(e)
    return {"traceEvents": out, "displayTimeUnit": "ms"}
