"""Serving SLOs: declarative objectives, rolling windows, burn rates.

Rounds 8–9 gave the service eyes (spans, ledgers, Prometheus); this
module closes the loop by giving it an *objective*: a declarative
:class:`Objective` states what fraction of events must be good
("99 % of requests under 50 ms", "99.9 % of solves succeed", "90 % of
factor lookups hit the cache", "99.9 % of budget checks stay inside
HBM"), and an :class:`SloTracker` evaluates each objective over
rolling time windows using the standard SRE **burn-rate** formula:

    error budget = 1 − target
    burn rate(window) = (bad / total over the window) / error budget

Burn rate 1.0 means the service is consuming its error budget exactly
at the allowed rate; 10 means the budget burns 10× too fast. An
objective **breaches** when EVERY configured window (conventionally a
short window for recency and a long one for significance — the
multi-window multi-burn-rate alerting rule) has traffic and a burn
rate above ``burn_threshold``; requiring all windows keeps one
transient spike (short dirty, long clean) and one stale incident
(long dirty, short clean) from paging.

Event flow: the serving runtime feeds the tracker at the points where
it already counts metrics — request/solve resolution (op, n, latency,
ok), factor-cache hits/misses, HBM-budget checks — guarded by one
``session.slo is not None`` test, so the disabled path allocates
NOTHING (the round-8 acceptance, extended to this module by test).
Breaches feed the existing warning path (the ``slate_tpu.obs`` logger
the slow-request log uses), bump the ``slo_breaches_total`` counter,
set per-objective burn-rate/breach gauges on the bound Metrics (hence
Prometheus), and emit an anomaly event span when tracing is on. The
``/slo`` endpoint on ``ObsServer`` serves :meth:`SloTracker.evaluate`
as JSON.

Stdlib-only and jax-free (the obs import rule).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Deque, Dict, Optional, Sequence, Tuple

from .tracing import log

# (short, long) rolling windows, seconds. Production SRE practice uses
# e.g. (300, 3600); the default keeps the short window useful in tests
# and smoke runs while the long window is the significance check.
DEFAULT_WINDOWS: Tuple[float, ...] = (60.0, 3600.0)

KINDS = ("latency", "error_rate", "cache_hit_rate", "oom_risk",
         "residual")


def n_bucket(n: int) -> int:
    """Pow2 size bucket of a problem dimension — the same quantization
    the batch engine uses (linalg/batched.batch_bucket), duplicated
    here without the jax import: SLO scopes speak the bucket
    vocabulary so one objective covers every n the bucket serves."""
    n = max(int(n), 1)
    return 1 << (n - 1).bit_length()


@dataclasses.dataclass(frozen=True)
class Objective:
    """One declarative SLO.

    ``target`` is the good-event fraction in (0, 1) — e.g. 0.99 means
    "99 % of events must be good"; the error budget is 1 − target.
    ``kind`` selects the event stream and the goodness predicate:

    * ``latency``        — request/solve events; good = succeeded AND
      ``latency_s <= threshold_s`` (``threshold_s`` required).
    * ``error_rate``     — request/solve events; good = succeeded.
    * ``cache_hit_rate`` — factor-cache accesses; good = hit.
    * ``oom_risk``       — HBM budget checks; good = within budget.
    * ``residual``       — sampled residual probes (round 16,
      obs/numerics); good = the probe's scaled residual
      ρ = ‖b−Ax‖/(‖A‖·‖x‖+‖b‖) ≤ ``threshold_s`` (the field is
      reused as the dimensionless ρ bound — one threshold slot, two
      value-vs-bound kinds).

    ``op``/``n_bucket`` scope latency/error objectives to one operator
    kind and/or one pow2 size bucket (None = all); ``source`` selects
    the stream: "request" (Batcher resolution — queue wait included,
    the client-visible number) or "solve" (Session device dispatch).
    """

    name: str
    kind: str
    target: float
    threshold_s: Optional[float] = None
    op: Optional[str] = None
    n_bucket: Optional[int] = None
    source: str = "request"
    # round 15: scope latency/error objectives to one tenant's traffic
    # (the runtime labels request events with the resolved tenant when
    # attribution or an explicit tenant= override is in play; events
    # without a tenant label carry None and only match unscoped
    # objectives)
    tenant: Optional[str] = None
    windows: Tuple[float, ...] = DEFAULT_WINDOWS
    burn_threshold: float = 1.0

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"Objective {self.name!r}: unknown kind "
                             f"{self.kind!r} (one of {KINDS})")
        if not (0.0 < self.target < 1.0):
            raise ValueError(f"Objective {self.name!r}: target must be in "
                             f"(0, 1), got {self.target}")
        if self.kind in ("latency", "residual") and not self.threshold_s:
            raise ValueError(f"Objective {self.name!r}: {self.kind} "
                             "objectives need threshold_s")
        if not self.windows:
            raise ValueError(f"Objective {self.name!r}: needs >= 1 window")

    @property
    def budget(self) -> float:
        return 1.0 - self.target


def default_objectives(latency_threshold_s: float = 0.25,
                       windows: Tuple[float, ...] = DEFAULT_WINDOWS
                       ) -> Tuple[Objective, ...]:
    """The serving defaults: request latency, request errors, factor
    cache hit rate, HBM OOM risk — one of each kind, unscoped."""
    return (
        Objective("request_latency", "latency", 0.99,
                  threshold_s=latency_threshold_s, windows=windows),
        Objective("request_errors", "error_rate", 0.999, windows=windows),
        Objective("factor_cache_hit_rate", "cache_hit_rate", 0.90,
                  windows=windows),
        Objective("hbm_oom_risk", "oom_risk", 0.999, windows=windows),
    )


# one recorded event: (t, latency_s, ok) for request streams,
# (t, 0.0, ok) for the cache/oom streams
_Event = Tuple[float, float, bool]


class SloTracker:
    """Rolling-window SLO evaluation over runtime-fed events.

    Thread-safe; events arrive from the Executor worker and the Session
    lock scope, evaluation from the ObsServer scrape thread. Streams
    are bounded deques (oldest events fall off; the windows are what
    give the numbers meaning anyway). ``clock`` is injectable and every
    record method takes an explicit ``t`` so the burn-rate math is
    pinnable without sleeping."""

    def __init__(self, objectives: Optional[Sequence[Objective]] = None,
                 metrics=None, tracer=None, max_events: int = 8192,
                 clock=time.monotonic):
        self.objectives: Tuple[Objective, ...] = tuple(
            default_objectives() if objectives is None else objectives)
        self.metrics = metrics
        self.tracer = tracer
        # incident hook (obs/recorder.py): a breach TRANSITION (ok ->
        # breached, already deduped under the lock below) triggers
        # black-box capture; None = one is-None check
        self.recorder = None
        self._clock = clock
        self._max = max_events
        self._lock = threading.Lock()
        # (source, op, n_bucket, tenant) -> events; scoped lookups
        # filter keys (tenant None = unlabeled, round-15 scoping)
        self._requests: Dict[Tuple[str, str, int, Optional[str]],
                             Deque[_Event]] = {}
        self._cache: Deque[_Event] = deque(maxlen=max_events)
        self._oom: Deque[_Event] = deque(maxlen=max_events)
        # round 16: sampled-residual probe events (t, rho, True) — the
        # "value" slot carries the dimensionless scaled residual
        self._resid: Deque[_Event] = deque(maxlen=max_events)
        self._breached: Dict[str, bool] = {}

    # -- recording (the runtime's hot path: one lock, one append) ----------

    def record_request(self, op: str, n: int, latency_s: float,
                       ok: bool = True, source: str = "request",
                       t: Optional[float] = None,
                       tenant: Optional[str] = None):
        key = (source, op, n_bucket(n), tenant)
        t = self._clock() if t is None else t
        with self._lock:
            q = self._requests.get(key)
            if q is None:
                q = self._requests[key] = deque(maxlen=self._max)
            q.append((t, float(latency_s), bool(ok)))

    def record_cache(self, hit: bool, t: Optional[float] = None):
        t = self._clock() if t is None else t
        with self._lock:
            self._cache.append((t, 0.0, bool(hit)))

    def record_oom(self, ok: bool, t: Optional[float] = None):
        """One HBM budget check: ok = resident + transient fit."""
        t = self._clock() if t is None else t
        with self._lock:
            self._oom.append((t, 0.0, bool(ok)))

    def record_residual(self, rho: float, t: Optional[float] = None):
        """One sampled residual probe (round 16): the scaled residual
        ρ rides the value slot; goodness is judged against each
        residual objective's threshold at evaluation time."""
        t = self._clock() if t is None else t
        with self._lock:
            self._resid.append((t, float(rho), True))

    def worst_burn_rate(self, now: Optional[float] = None) -> float:
        """Worst SHORT-window burn rate across objectives right now —
        the cheap point read the round-14 load shedder polls (full
        :meth:`evaluate` walks every window, publishes gauges, and
        detects breach transitions; an overload check needs none of
        that). Objectives with no traffic in their short window
        contribute nothing. 0.0 when the service is clean."""
        now = self._clock() if now is None else now
        with self._lock:
            snapshots = [(obj, self._events_for(obj))
                         for obj in self.objectives]
        worst = 0.0
        for obj, events in snapshots:
            row = self._window_stats(obj, events, now, min(obj.windows))
            if row["burn_rate"] is not None:
                worst = max(worst, row["burn_rate"])
        return worst

    def tenant_burn_rates(self, now: Optional[float] = None
                          ) -> Dict[str, float]:
        """Worst SHORT-window burn rate per tenant, over the
        TENANT-SCOPED objectives only (``Objective(tenant=...)``) —
        the round-18 tenant-scoped shedding read: a Batcher with a
        tenant table polls this so a burning tenant sheds ITS OWN
        cheapest requests first instead of tripping the global
        trigger. Tenants whose scoped objectives have no short-window
        traffic contribute nothing; {} when no objective is
        tenant-scoped."""
        now = self._clock() if now is None else now
        with self._lock:
            snapshots = [(obj, self._events_for(obj))
                         for obj in self.objectives
                         if obj.tenant is not None]
        out: Dict[str, float] = {}
        for obj, events in snapshots:
            row = self._window_stats(obj, events, now, min(obj.windows))
            if row["burn_rate"] is not None:
                out[obj.tenant] = max(out.get(obj.tenant, 0.0),
                                      row["burn_rate"])
        return out

    # -- evaluation ---------------------------------------------------------

    def _events_for(self, obj: Objective) -> Tuple[_Event, ...]:
        """Caller holds the lock."""
        if obj.kind == "cache_hit_rate":
            return tuple(self._cache)
        if obj.kind == "oom_risk":
            return tuple(self._oom)
        if obj.kind == "residual":
            return tuple(self._resid)
        out = []
        for (source, op, nb, tenant), q in self._requests.items():
            if source != obj.source:
                continue
            if obj.op is not None and op != obj.op:
                continue
            if obj.n_bucket is not None and nb != obj.n_bucket:
                continue
            if obj.tenant is not None and tenant != obj.tenant:
                continue
            out.extend(q)
        return tuple(out)

    @staticmethod
    def _window_stats(obj: Objective, events, now: float,
                      window_s: float) -> dict:
        """One window's burn-rate row — THE formula (pinned by test):
        burn = (bad/total) / (1 − target); None fields while empty."""
        total = bad = 0
        lat = []
        lo = now - window_s
        for t, latency, ok in events:
            if t < lo or t > now:
                continue
            total += 1
            good = ok
            if obj.kind in ("latency", "residual"):
                # one value-vs-threshold predicate: seconds for
                # latency, the dimensionless scaled residual for
                # residual probes (round 16)
                good = ok and latency <= obj.threshold_s
                lat.append(latency)
            if not good:
                bad += 1
        row = {
            "window_s": window_s,
            "total": total,
            "bad": bad,
            "good_fraction": (1.0 - bad / total) if total else None,
            "burn_rate": (bad / total / obj.budget) if total else None,
        }
        if obj.kind == "latency" and lat:
            # the observed latency at the target quantile — the number
            # a threshold re-tune reads (nearest-rank)
            s = sorted(lat)
            idx = min(len(s) - 1, int(obj.target * len(s)))
            row["latency_at_target_quantile_s"] = s[idx]
        return row

    def evaluate(self, now: Optional[float] = None) -> dict:
        """The ``/slo`` payload: every objective's per-window burn
        rates + breach state. A breach transition (ok -> breached)
        warns on the slate_tpu.obs logger, bumps ``slo_breaches_total``,
        and emits an ``slo.breach`` anomaly event span when tracing is
        on; burn rates and breach flags land as gauges on the bound
        Metrics either way (the Prometheus surface)."""
        now = self._clock() if now is None else now
        with self._lock:
            snapshots = [(obj, self._events_for(obj))
                         for obj in self.objectives]
        rows = []
        breaches = 0
        for obj, events in snapshots:
            windows = [self._window_stats(obj, events, now, w)
                       for w in obj.windows]
            burns = [w["burn_rate"] for w in windows]
            breached = bool(burns) and all(
                b is not None and b > obj.burn_threshold for b in burns)
            worst = max((b for b in burns if b is not None), default=None)
            row = {
                "name": obj.name, "kind": obj.kind, "target": obj.target,
                "threshold_s": obj.threshold_s, "op": obj.op,
                "n_bucket": obj.n_bucket, "source": obj.source,
                "tenant": obj.tenant,
                "burn_threshold": obj.burn_threshold,
                "windows": windows, "worst_burn_rate": worst,
                "breached": breached,
            }
            rows.append(row)
            breaches += breached
            self._publish(obj, windows, worst, breached)
        return {"enabled": True, "now": now, "objectives": rows,
                "breached_count": breaches}

    def _publish(self, obj: Objective, windows, worst, breached: bool):
        # transition detection under the lock: two concurrent /slo
        # scrapes must not both observe ok->breached and double-count
        # the breach (ThreadingHTTPServer serves scrapes in parallel)
        with self._lock:
            was = self._breached.get(obj.name, False)
            self._breached[obj.name] = breached
        m = self.metrics
        if m is not None:
            for w in windows:
                if w["burn_rate"] is not None:
                    m.set_gauge(
                        f"slo_burn_rate:{obj.name}:w{int(w['window_s'])}",
                        w["burn_rate"])
            m.set_gauge(f"slo_breached:{obj.name}", 1.0 if breached else 0.0)
            if breached and not was:
                m.inc("slo_breaches_total")
        if breached and not was:
            log.warning(
                "SLO breach: %s (%s, target %.4g) burn rate %.3g over %s",
                obj.name, obj.kind, obj.target, worst,
                [w["window_s"] for w in windows])
            tr = self.tracer
            if tr is not None and tr.enabled:
                tr.finish_span(tr.start_span(
                    "slo.breach", kind="anomaly", objective=obj.name,
                    slo_kind=obj.kind, target=obj.target,
                    worst_burn_rate=worst))
            rec = self.recorder
            if rec is not None:
                rec.incident(
                    "slo_breach", key=obj.name,
                    context={"objective": obj.name, "kind": obj.kind,
                             "target": obj.target,
                             "worst_burn_rate": worst})
