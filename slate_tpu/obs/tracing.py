"""Structured request-scoped tracing: the span model.

The reference's observability is two-layered: ``trace::Block`` RAII
events gathered into an SVG timeline (include/slate/internal/Trace.hh,
src/auxiliary/Trace.cc:330-446) and the coarse per-phase ``timers`` map
the tester prints at --timer-level 2. ``utils.trace`` ports both; this
module grows them into what a *serving* stack needs: structured spans
with identity (trace-id, span-id, parent-id), attributes (op, shape,
dtype, nb, cache hit/miss, handle), error status, and request-scoped
propagation — a served solve yields a connected span TREE
(batch → request / solve → factor / dispatch / block), exportable as
Chrome-trace JSON (obs.export) next to the legacy SVG.

Design rules:

* **Disabled is free.** ``Tracer.span`` returns a shared no-op context
  manager when tracing is off — no Span allocation, no id counter
  bump, no lock. The runtime's hot path stays at its round-6 cost.
* **One clock, every view.** A finished span also feeds the legacy
  ``trace.timers`` map and (when ``trace.Trace`` is on) the SVG event
  list, so enabling spans never *loses* the coarse views — the span
  model subsumes ``utils.trace.phase``.
* **Propagation is a contextvar**, per thread of execution: nested
  ``with tracer.span(...)`` blocks parent automatically; the Batcher
  parents request spans onto the batch span explicitly (they begin
  life queued, outside any context — see runtime/batching.py).
* **Slow-request log + error capture.** Spans of kind ``"request"``
  whose total latency exceeds ``Tracer.slow_threshold`` land in a
  bounded ``slow_log`` (and a logging.warning); a span closed by an
  exception (or finished with ``error=``) records status="error" and
  the exception text — the Executor feeds failed-retry batches here.
"""

from __future__ import annotations

import contextvars
import itertools
import logging
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..utils import trace as legacy_trace

log = logging.getLogger("slate_tpu.obs")


class Span:
    """One timed, attributed node of a trace tree."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start", "end",
                 "attrs", "thread", "status", "error", "kind")

    def __init__(self, name: str, trace_id: int, span_id: int,
                 parent_id: Optional[int], start: float, thread: int,
                 kind: str = "internal"):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = start
        self.end: Optional[float] = None
        self.attrs: Dict[str, Any] = {}
        self.thread = thread
        self.status = "ok"
        self.error: Optional[str] = None
        self.kind = kind

    @property
    def duration(self) -> Optional[float]:
        return None if self.end is None else self.end - self.start

    def set(self, **attrs) -> "Span":
        """Attach attributes (op, shape, dtype, nb, cache hit, ...)."""
        self.attrs.update(attrs)
        return self

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name, "trace_id": self.trace_id,
            "span_id": self.span_id, "parent_id": self.parent_id,
            "start": self.start, "end": self.end, "thread": self.thread,
            "kind": self.kind, "status": self.status, "error": self.error,
            "attrs": dict(self.attrs),
        }


class _NoopSpan:
    """Shared do-nothing span: what disabled tracing hands out (no
    allocation on the hot path). Accepts the full Span surface."""

    __slots__ = ()

    def set(self, **attrs):
        return self

    duration = None
    attrs: Dict[str, Any] = {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP_SPAN = _NoopSpan()


class _SpanCtx:
    """Context manager for one live span: enters the contextvar scope
    (so nested spans parent onto it), records the exception on exit."""

    __slots__ = ("_tracer", "_span", "_token")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self._span = span
        self._token = None

    def __enter__(self) -> Span:
        self._token = self._tracer._current.set(self._span)
        return self._span

    def __exit__(self, etype, exc, tb):
        if self._token is not None:
            self._tracer._current.reset(self._token)
        self._tracer.finish_span(self._span, error=exc)
        return False


class Tracer:
    """Thread-safe span registry with contextvar propagation.

    ``on()``/``off()`` toggle recording; ``span(name, **attrs)`` is the
    primary entry (a context manager yielding the Span); ``start_span``
    / ``finish_span`` give split lifecycle for spans that outlive one
    lexical scope (the Batcher's request spans). ``spans()`` snapshots
    the finished-span list for export.
    """

    def __init__(self, slow_threshold: Optional[float] = None,
                 max_spans: int = 65536, max_slow: int = 256):
        self.enabled = False
        self.slow_threshold = slow_threshold
        # flight-recorder hook (obs/recorder.py): finished spans feed
        # its bounded ring; None = one is-None check, nothing else
        self.recorder = None
        self._lock = threading.Lock()
        self._spans: List[Span] = []
        self._max_spans = max_spans
        self._dropped = 0
        self.slow_log: "deque[Span]" = deque(maxlen=max_slow)
        self._ids = itertools.count(1)
        self._current: "contextvars.ContextVar[Optional[Span]]" = \
            contextvars.ContextVar("slate_tpu_span", default=None)

    # -- lifecycle ---------------------------------------------------------

    def on(self, slow_threshold: Optional[float] = None):
        if slow_threshold is not None:
            self.slow_threshold = slow_threshold
        self.enabled = True
        return self

    def off(self):
        self.enabled = False
        return self

    def clear(self):
        with self._lock:
            self._spans = []
            self._dropped = 0
        self.slow_log.clear()
        return self

    # -- recording ---------------------------------------------------------

    def current(self) -> Optional[Span]:
        return self._current.get()

    def span(self, name: str, kind: str = "internal", **attrs):
        """Context manager; yields the live Span (or the shared no-op
        when tracing is disabled — zero allocation)."""
        if not self.enabled:
            return NOOP_SPAN
        return _SpanCtx(self, self.start_span(name, kind=kind, **attrs))

    def start_span(self, name: str, parent: Optional[Span] = None,
                   kind: str = "internal", **attrs) -> Optional[Span]:
        """Open a span without entering its scope (it does NOT become
        the contextvar parent). Returns None when disabled, so callers
        can store the result unconditionally."""
        if not self.enabled:
            return None
        sid = next(self._ids)
        # a _NoopSpan parent (captured while tracing was off, e.g. the
        # Batcher's batch context before on()) has no identity — fall
        # back to the contextvar like an absent parent
        p = parent if isinstance(parent, Span) else self._current.get()
        if p is not None:
            trace_id, parent_id = p.trace_id, p.span_id
        else:
            trace_id, parent_id = sid, None
        span = Span(name, trace_id, sid, parent_id, time.perf_counter(),
                    threading.get_ident(), kind)
        if attrs:
            span.attrs.update(attrs)
        return span

    def finish_span(self, span: Optional[Span],
                    parent: Optional[Span] = None,
                    error: Optional[BaseException] = None,
                    **attrs):
        """Close a span (idempotent; no-op on None). ``parent`` re-homes
        the span into the parent's trace (the Batcher adopts queued
        request spans into the batch trace this way)."""
        if span is None or isinstance(span, _NoopSpan) or span.end is not None:
            return
        span.end = time.perf_counter()
        if attrs:
            span.attrs.update(attrs)
        if parent is not None and not isinstance(parent, _NoopSpan):
            span.parent_id = parent.span_id
            span.trace_id = parent.trace_id
        if error is not None:
            span.status = "error"
            span.error = f"{type(error).__name__}: {error}"
        dur = span.end - span.start
        # bridge to the coarse legacy views: the span model subsumes
        # utils.trace.phase (timers map + SVG timeline)
        legacy_trace.add_timer(span.name, dur)
        if legacy_trace.Trace.enabled:
            legacy_trace.Trace.record(span.name, span.start, span.end)
        with self._lock:
            if len(self._spans) < self._max_spans:
                self._spans.append(span)
            else:
                self._dropped += 1
        rec = self.recorder
        if rec is not None:
            rec.span_finished(span)
        if span.kind == "request" and self.slow_threshold is not None:
            total = float(span.attrs.get("total_s", dur))
            if total >= self.slow_threshold:
                self.slow_log.append(span)
                log.warning(
                    "slow request: %s %.3f ms (threshold %.3f ms) attrs=%s",
                    span.name, total * 1e3, self.slow_threshold * 1e3,
                    span.attrs)

    def event(self, name: str, kind: str = "event", **attrs
              ) -> Optional[Span]:
        """Record a zero-duration marker span (SLO breaches, watchdog
        anomalies): opened and finished in one call, parented on the
        current context. No-op (None, no allocation) when disabled."""
        if not self.enabled:
            return None
        span = self.start_span(name, kind=kind, **attrs)
        self.finish_span(span)
        return span

    # -- introspection -----------------------------------------------------

    def spans(self) -> List[Span]:
        """Snapshot of finished spans (recording order)."""
        with self._lock:
            return list(self._spans)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def trace_tree(self) -> Dict[Optional[int], List[Span]]:
        """parent_id -> children map over the finished spans."""
        tree: Dict[Optional[int], List[Span]] = {}
        for s in self.spans():
            tree.setdefault(s.parent_id, []).append(s)
        return tree


# process-wide default tracer: disabled until someone opts in (the
# serving session, tools/obs_dump.py, the tester's --trace flag)
_DEFAULT = Tracer()


def default_tracer() -> Tracer:
    return _DEFAULT
