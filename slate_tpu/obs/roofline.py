"""Roofline/arithmetic-intensity reporting: flops ÷ bytes, per op.

Joins the round-8 FLOP ledger (obs/flops.py — model flops per driver
verb) with the round-9 bytes ledger (obs/costs.py — XLA bytes-accessed
and collective traffic per executed program) into the rows a roofline
analysis needs: arithmetic intensity (flops/byte), measured GFLOP/s and
GB/s (joined against the phase-timer map like ``gflops_report``), and —
when a machine model is known — which roof bounds the op and the
attainable rate.

The machine model is explicit, never guessed: pass a
:class:`MachineModel` or set ``SLATE_TPU_PEAK_GFLOPS`` /
``SLATE_TPU_HBM_GBPS`` in the environment (per-chip numbers; for the
BASELINE pod run the ICI roof matters too — ``ici_gbps``). Without one,
rows still carry intensity and measured rates; the bound/attainable
columns are ``None`` (an honest roofline needs a measured roof, PERF.md
Round 9).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional

from . import costs as costs_mod
from . import flops as flops_mod


@dataclasses.dataclass
class MachineModel:
    """Per-chip roofs (GFLOP/s, GB/s). ``ridge`` = flops/byte at which
    the compute roof takes over from the HBM roof."""

    peak_gflops: float
    hbm_gbps: float
    ici_gbps: Optional[float] = None
    name: str = "custom"

    @property
    def ridge(self) -> float:
        return self.peak_gflops / self.hbm_gbps

    def attainable_gflops(self, intensity: float) -> float:
        """min(compute roof, intensity × bandwidth roof)."""
        return min(self.peak_gflops, intensity * self.hbm_gbps)

    @classmethod
    def from_env(cls) -> Optional["MachineModel"]:
        peak = os.environ.get("SLATE_TPU_PEAK_GFLOPS")
        bw = os.environ.get("SLATE_TPU_HBM_GBPS")
        if not peak or not bw:
            return None
        ici = os.environ.get("SLATE_TPU_ICI_GBPS")
        return cls(float(peak), float(bw),
                   float(ici) if ici else None, name="env")


def intensity(flops: Optional[float],
              bytes_: Optional[float]) -> Optional[float]:
    """Arithmetic intensity; None when either axis is unknown."""
    if flops is None or not bytes_:
        return None
    return flops / bytes_


def roofline_row(op: str, flops: Optional[float], bytes_: Optional[float],
                 seconds: float = 0.0,
                 collective_bytes: Optional[float] = None,
                 machine: Optional[MachineModel] = None) -> dict:
    """One roofline row. ``seconds`` > 0 adds measured GFLOP/s + GB/s;
    a machine model adds the bound ("memory"/"compute") and the
    attainable rate the measurement should be compared against."""
    ai = intensity(flops, bytes_)
    row = {
        "op": op,
        "flops": flops,
        "bytes": bytes_,
        "collective_bytes": collective_bytes,
        "intensity": ai,
        "seconds": seconds or None,
        "gflops": (flops / seconds / 1e9
                   if flops is not None and seconds > 0 else None),
        "gbps": (bytes_ / seconds / 1e9
                 if bytes_ and seconds > 0 else None),
        "bound": None,
        "attainable_gflops": None,
        "roof_fraction": None,
    }
    if machine is not None and ai is not None:
        row["bound"] = "memory" if ai < machine.ridge else "compute"
        row["attainable_gflops"] = machine.attainable_gflops(ai)
        if row["gflops"] is not None and row["attainable_gflops"]:
            row["roof_fraction"] = row["gflops"] / row["attainable_gflops"]
    return row


def roofline_report(ledger: Optional[flops_mod.FlopLedger] = None,
                    bytes_ledger: Optional[costs_mod.BytesLedger] = None,
                    timers: Optional[Dict[str, float]] = None,
                    machine: Optional[MachineModel] = None) -> dict:
    """Join the process flop + bytes ledgers (default) against the
    phase-timer map: one roofline row per op that BOTH ledgers know
    (the served verbs — serve.factor/serve.solve — and any analyzed
    mesh driver), plus flop-only rows for ops with no byte telemetry
    (the eager verbs XLA never analyzed), flagged ``bytes: None``."""
    ledger = ledger if ledger is not None else flops_mod.LEDGER
    bytes_ledger = (bytes_ledger if bytes_ledger is not None
                    else costs_mod.BYTES)
    if timers is None:
        from ..utils.trace import timers as timers_
        timers = dict(timers_)
    if machine is None:
        machine = MachineModel.from_env()
    fsnap = ledger.snapshot()
    bsnap = bytes_ledger.snapshot()
    rows = []
    ops = sorted(set(fsnap["per_op"]) | set(bsnap["per_op"]))
    for op in ops:
        fl = fsnap["per_op"].get(op)
        brow = bsnap["per_op"].get(op)
        secs = timers.get(f"api.{op}", 0.0) or timers.get(op, 0.0)
        rows.append(roofline_row(
            op, fl, brow["bytes"] if brow else None, secs,
            brow["collective_bytes"] if brow else None, machine))
    return {
        "machine": dataclasses.asdict(machine) if machine else None,
        "flops_total": fsnap["flops_total"],
        "bytes_total": bsnap["bytes_total"],
        "collective_bytes_total": bsnap["collective_bytes_total"],
        "rows": rows,
    }
