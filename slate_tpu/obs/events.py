"""Decision-event vocabulary: the journal's schema and parity map.

Every autonomous reflex in the serving runtime announces itself twice:
a metric counter bump (the fleet-dashboard aggregate, unchanged) and —
when a :class:`~slate_tpu.obs.recorder.Recorder` is enabled — ONE
structured :class:`DecisionEvent` into the bounded decision journal.
The counter says *how many times*; the event says *what the system
knew when it decided* (queue depth, burn rate, headroom, condest,
measured win — the inputs an autoscaler policy or a post-incident
reader replays). SLATE's own per-rank trace payloads play the same
role for the reference factorizations: counters alone cannot order a
cascade (shed → breaker trip → failover) across subsystems, the
journal can (DESIGN.md round 22).

:data:`KIND_COUNTERS` is the single source of truth binding each
decision kind to the metric counter its seam has always incremented —
the parity invariant ``journal count(kind) == counter delta`` is
pinned per kind by test and exit-gated by the chaos recorder drill.
:data:`OUTCOME_COUNTERS` covers the seams that count one decision
under TWO counters (a tenant-LRU eviction bumps both ``evictions``
and ``tenant_quota_evictions_total``): the journal still records ONE
event, outcome-tagged, and the secondary counter's parity is checked
against the (kind, outcome) slice.

Stdlib-only (the obs import rule): the journal schema must be
readable by jax-free tooling (tools/bench_gate.py mirrors the
incident validator; tests pin the mirrors equal).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Dict, Iterable, Optional, Tuple

JOURNAL_SCHEMA = "slate_tpu.journal.v1"
INCIDENT_SCHEMA = "slate_tpu.incident.v1"

# decision kind -> the metric counter the same seam increments; the
# parity map (module docstring). A kind's journal counts sum event
# ``count`` (a shed wave drops N requests in ONE decision; a
# clear_cache evicts N residents in ONE sweep).
KIND_COUNTERS: Dict[str, str] = {
    # serving-door reflexes (runtime/batching.py)
    "shed": "shed_requests_total",
    "admission_reject": "admission_rejected_total",
    "quota_reject": "quota_rejections_total",
    "deadline_expired": "deadline_expired_total",
    # circuit breaker transitions (runtime/executor.py)
    "breaker_open": "breaker_trips_total",
    "breaker_probe": "breaker_probes_total",
    "breaker_close": "breaker_closes_total",
    # precision / health reflexes (runtime/session.py)
    "refine_fallback": "refine_fallbacks_total",
    "refine_demotion": "refine_demotions_total",
    "health_demotion": "health_demotions_total",
    "eviction": "evictions",
    "update_refactor": "update_refactors_total",
    # fleet coordinator reflexes (runtime/fleet.py)
    "failover": "fleet_failover_handles_total",
    "migration": "fleet_migrations_total",
    "migration_abort": "fleet_migration_aborts_total",
    "delta_sync": "fleet_delta_replications_total",
    "full_sync": "fleet_full_replications_total",
    # online shadow tuner (tuning/shadow.py)
    "tuner_promote": "tuner_promotions_total",
    "tuner_reject": "tuner_rejections_total",
    "tuner_demote": "tuner_demotions_total",
}

# (kind, outcome) -> the SECOND counter the same single decision
# bumps; parity for these checks the outcome-tagged journal slice.
OUTCOME_COUNTERS: Dict[Tuple[str, str], str] = {
    ("eviction", "tenant_quota"): "tenant_quota_evictions_total",
    ("update_refactor", "budget"): "update_budget_refactors_total",
    ("failover", "replica"): "fleet_failover_replica_served",
    ("failover", "restored"): "fleet_failover_restored",
    ("failover", "refactor"): "fleet_failover_refactor",
    ("failover", "cold"): "fleet_failover_cold",
}

DECISION_KINDS: Tuple[str, ...] = tuple(sorted(KIND_COUNTERS))

# the fields the same-seed chaos digest hashes: deterministic under a
# fixed fault schedule (timestamps and measured inputs are not)
DIGEST_FIELDS: Tuple[str, ...] = ("kind", "op", "handle", "tenant",
                                  "outcome", "count")


@dataclasses.dataclass(slots=True)
class DecisionEvent:
    """One reflex decision: what fired, over what scope, driven by
    which inputs, with which outcome. ``count`` carries multi-victim
    decisions (one shed wave, one eviction sweep); ``trace_id``/
    ``span_id`` join the event to the flight recorder's span ring."""

    seq: int
    ts: float
    kind: str
    op: Optional[str] = None
    handle: Optional[str] = None
    tenant: Optional[str] = None
    inputs: Optional[dict] = None
    outcome: Optional[str] = None
    count: float = 1.0
    trace_id: Optional[int] = None
    span_id: Optional[int] = None

    def to_dict(self) -> dict:
        return {
            "seq": self.seq, "ts": self.ts, "kind": self.kind,
            "op": self.op, "handle": self.handle, "tenant": self.tenant,
            "inputs": self.inputs, "outcome": self.outcome,
            "count": self.count, "trace_id": self.trace_id,
            "span_id": self.span_id,
        }


def journal_digest(events: Iterable) -> str:
    """Stable digest over the journal's deterministic fields
    (:data:`DIGEST_FIELDS`) in recording order — the reproducibility
    token the chaos recorder drill compares across same-seed runs
    (the journal twin of ``FaultInjector.schedule_digest``). Accepts
    :class:`DecisionEvent` objects or their dicts."""
    rows = []
    for e in events:
        d = e.to_dict() if isinstance(e, DecisionEvent) else e
        rows.append([d.get(f) for f in DIGEST_FIELDS])
    payload = json.dumps(rows, separators=(",", ":"))
    return "sha256:" + hashlib.sha256(payload.encode()).hexdigest()


# -- the incident schema ------------------------------------------------------

# every top-level key an incident document carries (the capture
# sections are nullable — a session without numerics enabled writes
# null, never omits the key); tools/bench_gate.py mirrors this tuple
# (tests pin the mirrors equal and feed both validators the same
# malformed docs — the checkpoint/placement discipline).
INCIDENT_KEYS: Tuple[str, ...] = (
    "schema", "id", "ts", "host", "reason", "key", "context",
    "journal", "flight", "metrics", "numerics", "quotas", "placement",
    "cost_log", "tuning")


def validate_incident(doc) -> list:
    """Validate one ``slate_tpu.incident.v1`` document; returns a list
    of error strings (empty = valid). This is the runtime-side
    validator; ``tools/bench_gate.py --check-schema`` applies a
    jax-free mirror to committed artifacts (drift-pinned by test)."""
    errs = []
    if not isinstance(doc, dict):
        return [f"incident: not a dict ({type(doc).__name__})"]
    if doc.get("schema") != INCIDENT_SCHEMA:
        errs.append(f"incident: schema {doc.get('schema')!r} != "
                    f"{INCIDENT_SCHEMA!r}")
    for k in INCIDENT_KEYS:
        if k not in doc:
            errs.append(f"incident: missing key {k!r}")
    if errs:
        return errs
    if not isinstance(doc["id"], str) or not doc["id"]:
        errs.append("incident: id must be a nonempty string")
    if not isinstance(doc["ts"], (int, float)):
        errs.append("incident: ts must be a number")
    if not isinstance(doc["reason"], str) or not doc["reason"]:
        errs.append("incident: reason must be a nonempty string")
    j = doc["journal"]
    if not isinstance(j, dict) or "events" not in j or "counts" not in j:
        errs.append("incident: journal must carry events + counts")
    else:
        if not isinstance(j["events"], list):
            errs.append("incident: journal.events must be a list")
        else:
            for i, ev in enumerate(j["events"]):
                if (not isinstance(ev, dict) or not ev.get("kind")
                        or not isinstance(ev.get("ts"), (int, float))
                        or not isinstance(ev.get("count"),
                                          (int, float))):
                    errs.append(f"incident: journal.events[{i}] "
                                "malformed (kind/ts/count)")
                    break
        if not isinstance(j["counts"], dict):
            errs.append("incident: journal.counts must be a dict")
    fl = doc["flight"]
    if (not isinstance(fl, dict)
            or not isinstance(fl.get("spans"), list)
            or not isinstance(fl.get("samples"), list)):
        errs.append("incident: flight must carry spans + samples lists")
    m = doc["metrics"]
    if (not isinstance(m, dict)
            or not isinstance(m.get("counters"), dict)
            or not isinstance(m.get("gauges"), dict)):
        errs.append("incident: metrics must carry counters + gauges")
    return errs
