"""Cost-model observability: the HBM/bytes and collective-traffic half.

The round-8 FLOP ledger (obs/flops.py) made model *flops* a first-class
process counter; this module does the same for the other two axes of
SLATE's performance story — **memory** and **communication** (the
reference credits its wins to tile residency and to hiding the
2D-block-cyclic communication, SURVEY §2.2/§3.5; the BASELINE pod run
is HBM- and ICI-bound, not flop-bound):

* :func:`program_costs` harvests XLA's own analyses off a compiled
  executable — ``Compiled.cost_analysis()`` (flops, bytes-accessed),
  ``Compiled.memory_analysis()`` (argument/output/temp bytes), and a
  collective census parsed from the optimized HLO text
  (``Compiled.as_text()``): one row per all-reduce / all-gather /
  reduce-scatter / collective-permute / all-to-all instruction with
  payload bytes, replica-group size, and modeled interconnect traffic.
  Every source degrades gracefully (XLA:CPU returns no temp sizes and
  sometimes no per-op breakdown): missing axes come back ``None`` and
  ``ProgramCosts.partial`` is set, never an exception on the serving
  path.

* :class:`BytesLedger` is the process-wide monotone **bytes** ledger —
  the peer of ``flops.LEDGER``. Executed programs credit bytes-accessed
  and collective traffic per *execution* (same discipline as the flop
  ledger: compile-time tracing credits nothing). Prometheus exposition
  renders it as ``slate_tpu_driver_bytes_total`` /
  ``slate_tpu_collective_bytes_total`` (obs/exposition.py); the
  roofline join (obs/roofline.py) divides the flop ledger by it.

* :func:`call_analyzed` instruments the explicitly-scheduled mesh
  drivers (parallel/summa.py, parallel/panel.py): first call per shape
  AOT-lowers the jitted driver once for analysis (cached), every call
  credits the ledger with the program's collective traffic — the
  telemetry the shard_map drivers never had.

Traffic model (per collective instruction, payload ``b`` bytes per
participant, group size ``g``): ring all-reduce moves ``2·(g−1)/g·b``
per participant; all-gather and reduce-scatter move ``(g−1)/g`` of the
gathered/scattered buffer; collective-permute and all-to-all move the
payload once. These are the standard bandwidth-optimal counts (the
reference's hypercube bcast/reduce overlays have the same asymptotics);
the census counts each HLO instruction once, EXCEPT inside ``while``
bodies whose instruction carries XLA's ``known_trip_count`` backend
config (round 10): those collectives are multiplied by the trip count,
because they execute once per iteration. A while without a trip count
(data-dependent loops) falls back to counted-once — so looped programs
report a LOWER bound exactly when XLA itself cannot bound the loop
(documented in PERF.md Rounds 9–10; nested whiles multiply by the
innermost counted loop only, again a lower bound).
"""

from __future__ import annotations

import dataclasses
import re
import threading
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

# NOTE: no jax import at module scope — importing slate_tpu.obs must
# stay jax-free (the round-8 rule); everything jax-touching resolves
# lazily inside functions.

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "s32": 4, "u32": 4,
    "s64": 8, "u64": 8, "f8e4m3fn": 1, "f8e5m2": 1, "bf16": 2, "f16": 2,
    "f32": 4, "f64": 8, "c64": 8, "c128": 16,
}

# %all-reduce.3 = f32[4,2]{1,0} all-reduce(...), replica_groups={{0,1},..}
_COLLECTIVE_RE = re.compile(
    r"=\s*(?:\()?\s*([a-z0-9]+)\[([0-9,]*)\][^=]*?"
    r"\b(all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(?:-start)?\(")
_GROUPS_RE = re.compile(r"replica_groups=\{\{([^}]*)\}")
# computation header: "%region_0.24 (args...) -> type {" / "ENTRY %main ..."
_COMP_RE = re.compile(r"^\s*(?:ENTRY\s+)?%?([\w.\-]+)\s+\([^=]*\)\s*->")
# while instr: "... while(%t), condition=%c, body=%region_0.24,
#   backend_config={"known_trip_count":{"n":"5"}}"
_WHILE_BODY_RE = re.compile(r"\bbody=%?([\w.\-]+)")
_TRIP_RE = re.compile(
    r"known_trip_count[\"']?\s*[:=]?\s*\{\s*[\"']?n[\"']?\s*[:=]?"
    r"\s*[\"']?(\d+)")
# XLA's iota form: replica_groups=[2,4]<=[8] — 2 groups of 4 (the
# common TPU spelling for sharded programs; the brace form above is
# what small CPU meshes emit)
_IOTA_GROUPS_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")
_PAIRS_RE = re.compile(r"source_target_pairs=\{\{")


@dataclasses.dataclass
class CollectiveCost:
    """Aggregated census of one collective kind in one program."""

    kind: str
    count: int = 0
    payload_bytes: int = 0       # per-shard payload summed over instrs
    traffic_bytes: int = 0       # modeled interconnect bytes (see model)
    group_size: int = 1          # largest replica group seen

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class ProgramCosts:
    """What XLA knows about one compiled program. ``None`` = the
    backend's analysis did not report that axis (``partial`` is set)."""

    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    argument_bytes: Optional[int] = None
    output_bytes: Optional[int] = None
    temp_bytes: Optional[int] = None
    peak_bytes: Optional[int] = None     # argument + output + temp
    collectives: Dict[str, CollectiveCost] = dataclasses.field(
        default_factory=dict)
    collective_bytes: int = 0            # total modeled traffic
    partial: bool = False

    @property
    def transient_bytes(self) -> int:
        """Execution-transient footprint beyond the program's inputs:
        temp scratch + freshly-allocated outputs. This is the number the
        Session adds on top of its cached-factor bytes when it checks
        the HBM budget (the inputs are the cached factor + the caller's
        operand, both already accounted)."""
        return int(self.temp_bytes or 0) + int(self.output_bytes or 0)

    def intensity(self) -> Optional[float]:
        """Arithmetic intensity (flops per byte accessed)."""
        if self.flops is None or not self.bytes_accessed:
            return None
        return self.flops / self.bytes_accessed

    def collective_counts(self) -> Dict[str, int]:
        """kind → instruction count (trip-count-weighted): the compact
        census summary the mesh-serving tests assert on — nonzero
        counts mean the scheduled HLO really contains collectives (the
        multichip artifact rows carry the same summary, built from the
        ``to_dict`` form in ``Session.cost_log``)."""
        return {k: c.count for k, c in self.collectives.items()}

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["collectives"] = {k: v.to_dict()
                            for k, v in self.collectives.items()}
        d["transient_bytes"] = self.transient_bytes
        d["intensity"] = self.intensity()
        return d


def collective_traffic(kind: str, payload: int, group: int) -> int:
    """Modeled interconnect bytes per participant for one collective
    (bandwidth-optimal algorithm counts — module docstring). A
    single-participant (or unparsed) group moves nothing, uniformly
    across kinds."""
    g = max(int(group), 1)
    if g <= 1:
        return 0
    if kind == "all-reduce":
        return int(2 * (g - 1) * payload / g)
    if kind in ("all-gather", "reduce-scatter"):
        return int((g - 1) * payload / g)
    # collective-permute / all-to-all: the payload crosses once
    return int(payload)


def _shape_bytes(dtype: str, dims: str) -> int:
    itemsize = _DTYPE_BYTES.get(dtype, 4)
    n = 1
    for d in dims.split(","):
        if d.strip():
            n *= int(d)
    return n * itemsize


def while_trip_counts(hlo_text: str) -> Dict[str, int]:
    """body-computation name → trip count, parsed off ``while``
    instructions whose ``backend_config`` carries XLA's
    ``known_trip_count`` estimate. Data-dependent loops (no trip count)
    are absent — their bodies fall back to counted-once."""
    out: Dict[str, int] = {}
    for line in hlo_text.splitlines():
        if "while(" not in line:
            continue
        bm = _WHILE_BODY_RE.search(line)
        tm = _TRIP_RE.search(line)
        if bm is not None and tm is not None:
            out[bm.group(1)] = max(int(tm.group(1)), 1)
    return out


def parse_collectives(hlo_text: str) -> Dict[str, CollectiveCost]:
    """Census of collective instructions in optimized HLO text: kind →
    aggregated (count, payload bytes, modeled traffic, group size).

    Computation-aware (round 10): a collective inside a while BODY
    whose ``while`` carries ``known_trip_count`` is credited once per
    iteration (count/payload/traffic × trip count); bodies of
    data-dependent loops keep the counted-once lower bound."""
    trips = while_trip_counts(hlo_text)
    out: Dict[str, CollectiveCost] = {}
    comp = None
    for line in hlo_text.splitlines():
        cm = _COMP_RE.match(line)
        if cm is not None and "{" in line:
            comp = cm.group(1)
            continue
        m = _COLLECTIVE_RE.search(line)
        if m is None:
            continue
        dtype, dims, kind = m.group(1), m.group(2), m.group(3)
        payload = _shape_bytes(dtype, dims)
        gm = _GROUPS_RE.search(line)
        im = _IOTA_GROUPS_RE.search(line)
        if gm is not None:
            group = len([t for t in gm.group(1).split(",") if t.strip()])
        elif im is not None:
            group = int(im.group(2))  # [n_groups, group_size]<=[total]
        elif _PAIRS_RE.search(line):
            group = 2  # permute: pairwise exchange
        else:
            group = 1
        mult = trips.get(comp, 1)
        cc = out.setdefault(kind, CollectiveCost(kind))
        cc.count += mult
        cc.payload_bytes += payload * mult
        cc.traffic_bytes += collective_traffic(kind, payload, group) * mult
        cc.group_size = max(cc.group_size, group)
    return out


def program_costs(compiled) -> ProgramCosts:
    """Harvest every analysis the backend offers off a jax ``Compiled``
    (``jit(f).lower(...).compile()``). Never raises: axes the backend
    cannot analyze come back ``None`` with ``partial=True``."""
    pc = ProgramCosts()
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        pc.flops = float(ca["flops"]) if "flops" in ca else None
        pc.bytes_accessed = (float(ca["bytes accessed"])
                             if "bytes accessed" in ca else None)
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        pc.argument_bytes = int(getattr(ma, "argument_size_in_bytes"))
        pc.output_bytes = int(getattr(ma, "output_size_in_bytes"))
        pc.temp_bytes = int(getattr(ma, "temp_size_in_bytes"))
        pc.peak_bytes = (pc.argument_bytes + pc.output_bytes
                         + pc.temp_bytes)
    except Exception:
        pass
    try:
        text = compiled.as_text()
        if text:
            pc.collectives = parse_collectives(text)
            pc.collective_bytes = sum(c.traffic_bytes
                                      for c in pc.collectives.values())
    except Exception:
        pass
    pc.partial = (pc.flops is None or pc.bytes_accessed is None
                  or pc.temp_bytes is None)
    return pc


def score_measured(model_flops: Optional[float], seconds: float,
                   bytes_accessed: Optional[float] = None,
                   machine=None) -> dict:
    """Join ONE measured slope-timed row with the program's
    compile-time cost analysis (the round-21 autotune scorer): always
    the measured GFLOP/s against the model-flop numerator; the
    arithmetic intensity when the backend reported bytes-accessed; and
    the roofline fraction/bound whenever a MachineModel is configured
    (``machine=`` or the SLATE_TPU_PEAK_GFLOPS/HBM_GBPS env — the
    round-9 roofline substrate, reused verbatim). CPU-smoke rows
    typically score gflops-only (XLA:CPU reports no byte analysis and
    no machine model is set) — honest degradation, the bench_gate
    platform policy."""
    from .roofline import MachineModel, roofline_row
    if machine is None:
        machine = MachineModel.from_env()
    row = roofline_row("tuning.candidate", model_flops, bytes_accessed,
                       seconds=seconds, machine=machine)
    return {k: row[k] for k in ("gflops", "gbps", "intensity", "bound",
                                "attainable_gflops", "roof_fraction")}


# -- process-wide bytes ledger ----------------------------------------------


class BytesLedger:
    """Monotone bytes accumulator per op — the memory/communication peer
    of :class:`flops.FlopLedger`. ``record`` credits one program
    *execution*; collective traffic is additionally broken out per
    collective kind (the fleet alarm is on ICI bytes, not op names)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._bytes_total = 0.0
        self._collective_total = 0.0
        self._per_op: Dict[str, Dict[str, float]] = {}
        self._per_kind: Dict[str, Dict[str, float]] = {}

    def record(self, op: str, bytes_accessed: float = 0.0,
               collective_bytes: float = 0.0,
               collectives: Optional[Dict[str, CollectiveCost]] = None):
        with self._lock:
            self._bytes_total += bytes_accessed
            self._collective_total += collective_bytes
            row = self._per_op.setdefault(
                op, {"bytes": 0.0, "collective_bytes": 0.0, "calls": 0})
            row["bytes"] += bytes_accessed
            row["collective_bytes"] += collective_bytes
            row["calls"] += 1
            for kind, cc in (collectives or {}).items():
                kr = self._per_kind.setdefault(
                    kind, {"bytes": 0.0, "count": 0})
                kr["bytes"] += cc.traffic_bytes
                kr["count"] += cc.count

    def record_costs(self, op: str, pc: ProgramCosts):
        """Credit one execution of an analyzed program."""
        self.record(op, pc.bytes_accessed or 0.0, pc.collective_bytes,
                    pc.collectives)

    @property
    def total(self) -> float:
        with self._lock:
            return self._bytes_total

    def reset(self):
        with self._lock:
            self._bytes_total = 0.0
            self._collective_total = 0.0
            self._per_op = {}
            self._per_kind = {}

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "bytes_total": self._bytes_total,
                "collective_bytes_total": self._collective_total,
                "per_op": {k: dict(v) for k, v in self._per_op.items()},
                "per_collective": {k: dict(v)
                                   for k, v in self._per_kind.items()},
            }


BYTES = BytesLedger()


# -- mesh-driver instrumentation --------------------------------------------

# per-(label, shapes) analysis cache: the mesh drivers rebuild their
# shard_map closure every call, so the memo key is structural
_ANALYSIS_LOCK = threading.Lock()
_ANALYSIS: "OrderedDict[Tuple, Tuple[Any, ProgramCosts]]" = OrderedDict()
_ANALYSIS_CAP = 64


def _arg_key(args) -> Tuple:
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    return (treedef,
            tuple((tuple(l.shape), str(getattr(l, "dtype", type(l))))
                  for l in leaves))


def call_analyzed(fn, args: Tuple, label: str,
                  ledger: Optional[BytesLedger] = None):
    """Run ``fn(*args)`` with cost telemetry: the first call per
    (label, arg-structure) AOT-compiles the program once for
    :func:`program_costs` (analysis cached) and executes through that
    same compiled program; later calls run ``fn`` exactly as the
    uninstrumented driver did (the mesh drivers rebuild their closure
    — alpha/beta and grid baked in — every call, so a compiled
    executable cannot be reused across calls) and EVERY call credits
    the bytes ledger — collective traffic included — under ``label``.

    Under an active jax trace (the driver is being composed into a
    larger jitted program) this degrades to a plain call: analysis and
    crediting belong to whoever compiles the outer program. Any
    analysis failure also degrades to the plain call — the telemetry
    must never take down the math."""
    from . import _jax_eager

    if not _jax_eager():
        return fn(*args)
    import jax

    key = (label,) + _arg_key(args)
    with _ANALYSIS_LOCK:
        hit = _ANALYSIS.get(key)
        if hit is not None:
            _ANALYSIS.move_to_end(key)
    led = ledger if ledger is not None else BYTES
    if hit is not None:
        led.record_costs(label, hit[1])
        return fn(*args)
    exe = None
    try:
        exe = jax.jit(fn).lower(*args).compile()
        pc = program_costs(exe)
    except Exception:
        exe, pc = None, ProgramCosts(partial=True)
    with _ANALYSIS_LOCK:
        _ANALYSIS[key] = (label, pc)
        while len(_ANALYSIS) > _ANALYSIS_CAP:
            _ANALYSIS.popitem(last=False)
    led.record_costs(label, pc)
    # the analysis compile serves this call's execution too — no
    # second trace+compile of the same program
    return exe(*args) if exe is not None else fn(*args)


def analyzed_costs(label: str) -> Dict[Tuple, ProgramCosts]:
    """Cached analyses recorded under ``label`` (for dumps/tests)."""
    with _ANALYSIS_LOCK:
        return {k: v[1] for k, v in _ANALYSIS.items() if k[0] == label}
