"""FLOP/byte ledger: ONE home for the model-GFLOP formulas.

Before this module the lawn41-convention flop models lived in three
places — bench.py (gemm/potrf/getrf/geqrf/heev/svd headline rows),
slate_tpu/tester.py (the ~40 ``register(..., flops=...)`` lambdas), and
runtime/session.py (``_factor_flops``/``_solve_flops`` feeding the
serving metrics) — three copies of the same numerator that could (and
did) drift. They are all defined here once, in the reference tester's
conventions (blas::Gflop as used by test/test_*.cc; lawn41 counts).

The module also keeps a process-wide :class:`FlopLedger`: every
simplified-API driver call (api.py) credits its model flops here, so
``flops_total`` is monotone across the whole process — not just inside
a serving Session — and per-phase GFLOP/s falls out of any snapshot by
dividing against the ``utils.trace.timers`` phase map (``gflops_report``
does exactly that). Prometheus exposition (obs/exposition.py) renders
the ledger as ``slate_tpu_driver_flops_total`` counters.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

# -- canonical model formulas (lawn41 / reference-tester conventions) -------


def gemm(m: int, n: int, k: int) -> float:
    return 2.0 * m * n * k


def symm(n: int) -> float:
    return 2.0 * n ** 3


def syrk(n: int) -> float:
    return float(n) ** 3


def syr2k(n: int) -> float:
    return 2.0 * n ** 3


def rank_k(n: int, k: int) -> float:
    """n×n rank-k update (syrk/herk actual count)."""
    return float(n) * n * k


def rank_2k(n: int, k: int) -> float:
    return 2.0 * n * n * k


def tri_mm(n: int, k: int) -> float:
    """n×n triangular times n×k (trmm/trsm actual count). For
    Side.Right pass k = the OTHER operand's row count — the model is
    n²·k either way with n the triangular dimension."""
    return float(n) * n * k


def band_mm(n: int, k: int, band: int) -> float:
    """Band matrix (stored bandwidth kl+ku = ``band``) times a k-wide
    operand: each of the n columns holds ≤ band+1 entries, one mul-add
    per entry per output column — NOT dense gemm (a kd-band multiply
    executes ~n/band of the dense count)."""
    return 2.0 * (band + 1) * n * k


def trmm(m: int, n: int) -> float:
    # reference-tester sweep convention (square triangular operand)
    return float(n) ** 3


def trsm(m: int, n: int) -> float:
    return float(n) ** 3


def trtri(n: int) -> float:
    return n ** 3 / 3.0


def potrf(n: int) -> float:
    return n ** 3 / 3.0


def potri(n: int) -> float:
    return 2.0 * n ** 3 / 3.0


def getrf(n: int, m: Optional[int] = None) -> float:
    # square convention throughout the sweeps; m kept for symmetry
    return 2.0 * n ** 3 / 3.0


def getri(n: int) -> float:
    return 2.0 * n ** 3


def geqrf(m: int, n: int) -> float:
    return 2.0 * m * n * n - 2.0 * n ** 3 / 3.0


def gelqf(m: int, n: int) -> float:
    return 2.0 * m * m * n - 2.0 * m ** 3 / 3.0


def gels(m: int, n: int) -> float:
    return 2.0 * m * n * n


def hetrf(n: int) -> float:
    return n ** 3 / 3.0


def heev(n: int, vectors: bool = False) -> float:
    """values: (4/3)n³ (the he2td reduction dominates); +2n³ for the
    eigenvector back-transform."""
    return (4.0 / 3.0 + (2.0 if vectors else 0.0)) * n ** 3


def heev_2stage(n: int) -> float:
    return 9.0 * n ** 3


def svd(m: int, n: int, vectors: bool = False) -> float:
    """values: (8/3)mn² (gebrd count); +4n³ for the U and V
    back-transforms (square-vectors convention of the tester)."""
    f = 8.0 * m * n * n / 3.0
    if vectors:
        f += 4.0 * n ** 3
    return f


def band_factor(n: int, band: int) -> float:
    """band = kl+ku (or kd for Hermitian): O(n·band²)."""
    return 2.0 * n * band * band if band else 2.0 * n


# -- incremental factor maintenance (round 20) ------------------------------


def update_chol(n: int, k: int) -> float:
    """Rank-k Cholesky up/downdate of a resident n×n L (GGMS '74 /
    Davis–Hager rotation sweep): each of the k vectors touches every
    column once — one rotation build + one length-(n-j) axpy pair per
    (column, vector), ~4·Σ(n-j) ≈ 2n² per vector."""
    return 2.0 * n * n * k


def update_qr(m: int, n: int, k: int) -> float:
    """Append k rows to a resident m×n QR: the structured factorization
    of [R; U] — per column j a length-k reflector applied to the n-j
    trailing columns of (R row j, U), ~6·Σ k·(n-j) ≈ 3n²k (build +
    two-sided apply; m enters only through the base factor, kept for
    signature symmetry)."""
    return 3.0 * n * n * k


def update_flops(op: str, m: int, n: int, k: int) -> float:
    """Model flops of one rank-k/row-k incremental update against a
    resident factor, keyed by the Session op kind (chol/chol_small
    share the dense model — the batched dispatch credits B×)."""
    if op in ("chol", "chol_small"):
        return update_chol(n, k)
    if op == "qr":
        return update_qr(m, n, k)
    raise ValueError(f"update_flops: unsupported op {op!r}")


# -- spectral two-stage per-stage models (round 19) -------------------------

# heev_2stage's 9n³ total splits across the staged programs roughly as
# he2hb (4/3)n³ + chase O(n²·nb) + the two back-transform sweeps ~2n³
# each + stedc merges; the per-stage table below names the dominant
# term of EACH analyzed program so the Session's cost_log rows carry a
# defensible model numerator (the round-6 bench convention: model the
# work the program body executes, not the end-to-end headline).
SPECTRAL_STAGE_MODELS: Dict[str, Callable[[int, int, int], float]] = {
    # (m, n, nb) -> flops; square ops ignore m
    "spectral.he2hb": lambda m, n, nb: 4.0 * n ** 3 / 3.0,
    "spectral.hb2td": lambda m, n, nb: 6.0 * n * n * nb,
    "spectral.unmtr": lambda m, n, nb: 4.0 * n ** 3,
    "spectral.heev_dense": lambda m, n, nb: heev(n, vectors=True),
    "spectral.ge2tb": lambda m, n, nb: 8.0 * m * n * n / 3.0,
    "spectral.tb2bd": lambda m, n, nb: 24.0 * n * n * nb,
    "spectral.unmbr": lambda m, n, nb: 2.0 * m * n * n + 2.0 * n ** 3,
    "spectral.svd_dense": lambda m, n, nb: svd(m, n, vectors=True),
}


def spectral_stage_flops(stage: str, m: int, n: int, nb: int) -> float:
    """Model flops of one staged spectral program (0 for unknown
    stages — the census still carries measured bytes)."""
    model = SPECTRAL_STAGE_MODELS.get(stage)
    return model(m, n, nb) if model else 0.0


# -- solve / factor dispatch (the serving Session's accounting) -------------


def factor_flops(op: str, m: int, n: int, band: int = 0) -> float:
    """Model flops of one factorization, keyed by the Session op kind
    ({lu, chol, qr, band_lu, band_chol, lu_small, chol_small, eig,
    svd} — the *_small ops are one ITEM of the batched engine: same
    per-item model, credited B× by the batched dispatch; eig/svd are
    the round-19 two-stage spectral registrations)."""
    if op in ("lu", "lu_small"):
        return getrf(n)
    if op in ("chol", "chol_small"):
        return potrf(n)
    if op == "qr":
        return geqrf(m, n)
    if op == "eig":
        return heev_2stage(n)
    if op == "svd":
        return svd(m, n, vectors=True)
    return band_factor(n, band)


def solve_flops(op: str, m: int, n: int, k: int, band: int = 0) -> float:
    """Model flops of a k-column solve against a resident factor."""
    if op in ("lu", "chol", "lu_small", "chol_small"):
        return 2.0 * n * n * k
    if op == "qr":
        return (4.0 * m * n - 2.0 * n * n) * k
    if op in ("eig", "svd"):
        # served spectral apply = two gemms against the resident bases
        # (+ a diagonal scale, O(nk), below model resolution)
        return 4.0 * m * n * k
    return 4.0 * n * band * k if band else 4.0 * n * k


# -- the tester's sweep models (m, n) -> flops ------------------------------

# the reference tester parameterizes every row by (m, n); these wrap the
# canonical formulas in that signature so tester.py registers against
# ONE table instead of inline lambdas
TESTER_MODELS: Dict[str, Callable[[int, int], float]] = {
    "gemm": lambda m, n: gemm(m, m, n),
    "symm": lambda m, n: symm(n),
    "hemm": lambda m, n: symm(n),
    "syrk": lambda m, n: syrk(n),
    "herk": lambda m, n: syrk(n),
    "syr2k": lambda m, n: syr2k(n),
    "her2k": lambda m, n: syr2k(n),
    "trmm": lambda m, n: trmm(m, n),
    "trsm": lambda m, n: trsm(m, n),
    "trtri": lambda m, n: trtri(n),
    "potrf": lambda m, n: potrf(n),
    "posv": lambda m, n: potrf(n),
    "potri": lambda m, n: potri(n),
    "posv_mixed": lambda m, n: potrf(n),
    "posv_mixed_gmres": lambda m, n: potrf(n),
    "getrf": lambda m, n: getrf(n),
    "gesv": lambda m, n: getrf(n),
    "gesv_nopiv": lambda m, n: getrf(n),
    "gesv_rbt": lambda m, n: getrf(n),
    "gesv_tntpiv": lambda m, n: getrf(n),
    "gesv_mixed": lambda m, n: getrf(n),
    "gesv_mixed_gmres": lambda m, n: getrf(n),
    # round 13: the served mixed paths use the per-item factor model
    # (refinement overhead is credited separately, as serve.refine —
    # the useful-vs-refinement ledger split); the batched tester rows
    # time a FIXED B=4 stack, so their model is 4x per-item — a row's
    # GFLOP/s column must describe the work its body executes
    "gesv_mixed_batched": lambda m, n: 4.0 * getrf(n),
    "posv_mixed_batched": lambda m, n: 4.0 * potrf(n),
    "gesv_mixed_served": lambda m, n: getrf(n),
    "posv_mixed_served": lambda m, n: potrf(n),
    "getri": lambda m, n: getri(n),
    "geqrf": geqrf,
    "gelqf": gelqf,
    "cholqr": gels,
    "gels": gels,
    "heev": lambda m, n: heev(n),
    "heev_2stage": lambda m, n: heev_2stage(n),
    "heev_vec": lambda m, n: heev_2stage(n),
    "hegv": lambda m, n: heev_2stage(n),
    "svd": svd,
    "svd_vec": lambda m, n: heev_2stage(n),
    "hesv": lambda m, n: hetrf(n),
    # round 20: incremental-maintenance rows use a FIXED k=4 (same
    # discipline as the batched rows' fixed B=4 — an (m, n) sweep row
    # must name the work its body executes); the serving ledger charges
    # the EXACT rank via update_flops(op, m, n, k)
    "potrf_update": lambda m, n: update_chol(n, 4),
    "geqrf_rowadd": lambda m, n: update_qr(m, n, 4),
}


def tester_model(name: str) -> Callable[[int, int], float]:
    """(m, n) -> model flops for a tester sweep row."""
    return TESTER_MODELS[name]


# -- process-wide ledger ----------------------------------------------------


class FlopLedger:
    """Monotone model-flop accumulator, per driver op. Thread-safe and
    cheap (one lock + two float adds per driver call)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._total = 0.0
        self._per_op: Dict[str, float] = {}
        self._calls: Dict[str, int] = {}

    def record(self, op: str, flops: float):
        with self._lock:
            self._total += flops
            self._per_op[op] = self._per_op.get(op, 0.0) + flops
            self._calls[op] = self._calls.get(op, 0) + 1

    @property
    def total(self) -> float:
        with self._lock:
            return self._total

    def reset(self):
        with self._lock:
            self._total = 0.0
            self._per_op = {}
            self._calls = {}

    def snapshot(self) -> dict:
        with self._lock:
            return {"flops_total": self._total,
                    "per_op": dict(self._per_op),
                    "calls": dict(self._calls)}

    def gflops_report(self, timers: Optional[Dict[str, float]] = None
                      ) -> dict:
        """Per-op flops joined against a phase-timer map (default: the
        legacy ``utils.trace.timers``): ops whose name matches a timer
        phase (``api.<op>``) get a measured GFLOP/s column. Round 9:
        ops the bytes ledger (obs/costs.py) also knows gain
        ``bytes`` / ``collective_bytes`` / ``intensity`` (flops per
        byte) columns — the roofline join, see obs/roofline.py for the
        full report with machine roofs."""
        if timers is None:
            from ..utils.trace import timers as timers_
            timers = timers_
        from . import costs as costs_mod
        bsnap = costs_mod.BYTES.snapshot()
        snap = self.snapshot()
        report = {}
        for op, fl in snap["per_op"].items():
            secs = timers.get(f"api.{op}", 0.0) or timers.get(op, 0.0)
            row = {
                "flops": fl,
                "calls": snap["calls"][op],
                "seconds": secs,
                "gflops": fl / secs / 1e9 if secs > 0 else None,
            }
            brow = bsnap["per_op"].get(op)
            if brow is not None:
                row["bytes"] = brow["bytes"]
                row["collective_bytes"] = brow["collective_bytes"]
                row["intensity"] = (fl / brow["bytes"]
                                    if brow["bytes"] else None)
            report[op] = row
        return {"flops_total": snap["flops_total"], "per_op": report}


LEDGER = FlopLedger()
