"""slate_tpu.obs — unified observability layer.

One span model flowing from the simplified-API drivers through the
serving runtime (Session/Batcher/Executor), exported in formats real
tools ingest:

* :mod:`.tracing`    — structured spans (trace/span/parent ids,
  attributes, error status), request-scoped propagation, slow-request
  log; subsumes ``utils.trace.phase`` (feeds the legacy timers map and
  SVG timeline on every span finish).
* :mod:`.export`     — Chrome-trace/Perfetto ``trace_event`` JSON
  (one lane per thread + one per phase class) with a schema validator.
* :mod:`.flops`      — the FLOP ledger: every model-GFLOP formula in
  one module (bench.py, tester.py, and runtime/session.py all import
  from here) plus the process-wide monotone flop counter the drivers
  credit.
* :mod:`.exposition` — Prometheus text rendering of runtime Metrics +
  an opt-in stdlib-only HTTP endpoint (/metrics, /healthz,
  /trace.json).
* :mod:`.merge`      — aligns host spans with ``jax.profiler`` device
  traces via the ``potrf_l{k}_*``/``geqrf_l{k}_*`` named scopes and
  computes the measured lookahead-overlap metric (PERF.md round 7's
  modeled number, measured); round 12 adds the multi-process trace
  combine (``combine_process_traces``).
* :mod:`.slo`        — declarative serving objectives evaluated over
  rolling windows with multi-window burn rates; the ``/slo`` endpoint
  payload (round 12).
* :mod:`.watchdog`   — online regression detection: live serving
  numbers vs the committed ``BASELINE_SERIES.json`` best-priors
  (bench_gate's tolerance policy), anomalies into trace + /metrics.
* :mod:`.aggregate`  — N processes' metric/ledger/trace snapshots
  folded into one fleet view (counters summed exactly, histograms
  merged, gauges host-labeled).
* :mod:`.attribution` — per-(tenant, handle) attribution of every
  counter class (flops/bytes/ICI/seconds/residency/outcomes) on exact
  dyadic grids, EWMA handle heat, and the placement-snapshot schema
  the fleet fold turns into ROADMAP item 1's placement input
  (round 15).
* :mod:`.events` / :mod:`.recorder` — the decision journal, flight
  recorder, and incident capture (round 22): every runtime reflex
  emits one structured :class:`~.events.DecisionEvent` (parity with
  its metric counter pinned per kind), recent spans + gauge samples
  ride bounded always-on rings, and anomaly/breach/breaker/fault
  transitions materialize rate-limited, deduped, crash-safe
  ``slate_tpu.incident.v1`` snapshots (the ``/journal`` +
  ``/incidents`` routes; fleet folds in :mod:`.aggregate`).
* :mod:`.timeseries` / :mod:`.forecast` — the telemetry-history layer
  (round 23): a bounded per-series store (raw rings + 10 s/60 s
  min/max/sum/count downsample tiers, counter-to-rate, hard
  cardinality cap) fed by a ``pump()``-style Session sampler, and
  deterministic trend/seasonality forecasting over it
  (autocorrelation periodicity, seasonal-naive/Holt-Winters with
  confidence bands, ``predicted_hot`` / ``time_to_exhaustion`` — the
  elastic-fleet sensing substrate; ``/history`` + ``/forecast``
  routes; fleet fold in :mod:`.aggregate`).
* :mod:`.numerics`   — numerical-health telemetry (round 16): the
  growth-bound machinery (one source of truth with the tester), the
  Hager/Higham condest loop the Session drives with resident-factor
  solve applies, the deterministic residual-probe sampler, and the
  per-handle healthy/degraded/suspect monitor with counted demotion
  and eviction reflexes.

See DESIGN.md "Observability (round 8)" for the reference mapping
(Trace.hh Block/SVG -> span model + Chrome export; the global timers
map / --timer-level -> Metrics histograms / Prometheus text).
"""

from . import (aggregate, attribution, costs, events, flops, forecast,
               numerics, recorder, roofline, slo, timeseries, watchdog)
from .attribution import AttributionLedger
from .events import DecisionEvent, journal_digest, validate_incident
from .export import chrome_trace, validate_chrome_trace, write_chrome_trace
from .exposition import ObsServer, render_prometheus
from .forecast import Forecaster, forecast_points, validate_forecast
from .timeseries import (SessionSampler, TimeseriesStore,
                         validate_timeseries)
from .merge import combine_process_traces, lookahead_overlap, merge_traces
from .numerics import NumericsConfig, NumericsMonitor
from .recorder import (DecisionJournal, FlightRecorder, IncidentCapture,
                       Recorder)
from .slo import Objective, SloTracker
from .tracing import NOOP_SPAN, Span, Tracer, default_tracer
from .watchdog import Watchdog

__all__ = [
    "AttributionLedger", "DecisionEvent", "DecisionJournal",
    "FlightRecorder", "Forecaster", "IncidentCapture", "NOOP_SPAN",
    "NumericsConfig",
    "NumericsMonitor", "Objective", "ObsServer", "Recorder",
    "SessionSampler", "SloTracker", "Span", "TimeseriesStore", "Tracer",
    "Watchdog", "aggregate", "attribution", "chrome_trace",
    "combine_process_traces",
    "costs", "default_tracer", "events", "flops", "forecast",
    "forecast_points", "journal_digest",
    "lookahead_overlap",
    "merge_traces", "numerics", "recorder", "render_prometheus",
    "roofline", "slo", "timeseries",
    "validate_chrome_trace", "validate_forecast", "validate_incident",
    "validate_timeseries", "watchdog",
    "write_chrome_trace",
]


_trace_state_clean = None


def _jax_eager() -> bool:
    """True when we are executing eagerly (NOT inside a jax trace).
    Driver calls re-executed by ``jax.jit`` tracing (the serving
    Session's compiled factor/solve programs call api.* verbs inside
    jit) must credit NOTHING: the trace runs once per compiled shape,
    not per execution — crediting there would freeze the ledger at
    ~one call per shape and record compile durations as spans. The
    probe resolves lazily so importing obs never imports jax."""
    global _trace_state_clean
    if _trace_state_clean is None:
        try:
            from jax.core import trace_state_clean as tsc
        except ImportError:
            try:
                from jax._src.core import trace_state_clean as tsc
            except ImportError:  # unknown jax: assume eager (pre-existing
                tsc = lambda: True  # noqa: E731 — behavior, never worse)
        _trace_state_clean = tsc
    return _trace_state_clean()


def driver(name: str, flops_value: float = 0.0, **attrs):
    """Driver-entry hook used by api.py: credits the process FLOP
    ledger on every EAGER call (flops_total stays monotone with
    tracing off) and opens an ``api.<name>`` span when the default
    tracer is on. Under a jax trace it is a no-op (see ``_jax_eager``);
    work executed through compiled programs is credited by its caller
    — the serving Session records its executed factor/solve flops as
    ``serve.factor``/``serve.solve`` ledger ops."""
    if not _jax_eager():
        return NOOP_SPAN
    if flops_value:
        flops.LEDGER.record(name, flops_value)
    t = default_tracer()
    if not t.enabled:
        return NOOP_SPAN
    return t.span(f"api.{name}", **attrs)
