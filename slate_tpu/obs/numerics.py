"""Numerical-health telemetry for served solves (round 16).

Rounds 8/12/14/15 taught the serving stack to watch its *performance*
(spans, SLO burn rates, fault reflexes, tenant attribution); nothing
watched *numerical quality* in production — the "never a wrong answer"
guarantee was exercised only by tests and chaos drills, and the
mixed-precision residents (refine/, PR 9's Carson & Higham ladder)
silently assume operands stay well-conditioned. This module is the
sensing layer (ROADMAP item 2 needs exactly these signals to decide
update-vs-refactor):

* **Growth bounds** — the realized element-growth factors the tester
  grew for its residual normalizations (``_chol_growth`` /
  ``_lu_growth`` / ``_aasen_growth``), promoted HERE as the one source
  of truth; ``tester.py`` imports them back. ‖L‖‖U‖/‖A‖ is the factor
  the LAPACK backward bound scales by — unbounded growth is the first
  factor-time symptom of a numerically hostile operand.
* **:func:`norm1est`** — Hager/Higham's 1-norm estimator (the
  SLICOT-style power iteration on sign vectors; LAPACK ``?gecon``,
  SLATE ``gecondest``/``pocondest`` via ``internal_norm1est``) as a
  HOST loop over caller-supplied solve callables. The serving Session
  drives it with a handful of extra ``*_solve_using_factor`` applies
  against the RESIDENT factor (runtime/session.condest), so a live
  condition estimate costs ~2·max_iter solves and zero refactors;
  ``linalg/condest`` adapts the same loop for the eager drivers.
* **:class:`ResidualSampler`** — a deterministic seeded sampler (Weyl
  sequence) deciding which served solves pay the fused
  ‖b−Ax‖/(‖A‖·‖x‖+‖b‖) residual probe; the decision stream is a pure
  function of (seed, request index), so probe schedules are
  reproducible inputs exactly like round-14 fault schedules.
* **:class:`NumericsMonitor`** — per-handle health state: condest /
  growth / sampled-residual EWMA / refine-iteration drift / NaN-Inf
  sentinels rolled into a ``healthy`` / ``degraded`` / ``suspect``
  classification, exported as ``handle_health:{tenant}:{handle}``
  gauges (dropped on forget — the round-15 cardinality discipline)
  and new columns on placement-snapshot rows. State transitions are
  counted (``health_transitions_total``) and logged; the Session's
  reflex hooks demote suspect handles off the refine ladder and
  deprioritize them at eviction tie-breaks — counted, never silent.

Thresholds are *dimensionless* multiples of the handle's unit
roundoff: the conditioning signal is u·κ(A) (u of the FACTOR dtype for
refined residents — the quantity Carson & Higham's convergence theory
bounds), the residual signal is ρ/eps(working). So one config covers
every dtype without per-dtype tables.

jax-free (the obs import rule); numpy only — the growth/estimator math
runs on host-gathered factors and host probe vectors, exactly like the
tester always did.
"""

from __future__ import annotations

import dataclasses
import math
import threading
from typing import Callable, Dict, Hashable, Optional, Tuple

import numpy as np

from .tracing import log

HEALTH_STATES = ("healthy", "degraded", "suspect")
_LEVEL = {s: i for i, s in enumerate(HEALTH_STATES)}

# unit roundoff per canonical dtype name; bfloat16 is not a numpy
# dtype, so the ladder entry is hardcoded (2^-8 — np.finfo semantics:
# eps is the gap above 1.0, 2^-7; half of it is the rounding unit.
# We store eps to match np.finfo(dtype).eps for the numpy dtypes.)
_EPS = {
    "float64": float(np.finfo(np.float64).eps),
    "float32": float(np.finfo(np.float32).eps),
    "float16": float(np.finfo(np.float16).eps),
    "bfloat16": 2.0 ** -7,
    "complex128": float(np.finfo(np.float64).eps),
    "complex64": float(np.finfo(np.float32).eps),
}


def dtype_eps(name) -> float:
    """eps of a canonical dtype name (refine/policy vocabulary);
    unknown names fall back to float64's eps (conservative: flags
    earlier, never later)."""
    return _EPS.get(str(name), _EPS["float64"])


# -- growth bounds (promoted from tester.py — one source of truth) ----------


def _np64(v) -> np.ndarray:
    """Dense float64/complex128 host copy of an array or a
    TiledMatrix-like (anything with ``dense_canonical``)."""
    if hasattr(v, "dense_canonical"):
        v = v.dense_canonical()
    v = np.asarray(v)
    return v.astype(np.complex128 if np.iscomplexobj(v) else np.float64)


def lu_growth(LU, a) -> float:
    """Realized element-growth factor ‖L‖₁‖U‖₁/‖A‖₁ (clamped ≥ 1) of a
    packed LU factor — the LAPACK residual normalization the pivoted LU
    tester rows use (‖b−Ax‖ ≲ ε·n·‖L‖‖U‖·‖x‖, test_gesv.cc). Accepts a
    TiledMatrix factor or a plain packed array (one item of a batched
    factor stack)."""
    lu = _np64(LU)
    npad = lu.shape[0]
    l = np.tril(lu, -1) + np.eye(npad)
    u = np.triu(lu)
    an = _np64(a)
    return max(1.0, np.linalg.norm(l, 1) * np.linalg.norm(u, 1)
               / max(np.linalg.norm(an, 1), 1e-300))


# the batched-stack alias tester.py round 13 grew; same formula, kept
# as a name so call sites read as "one item of a lo factor stack"
lu_growth_arr = lu_growth


def chol_growth(L, a) -> float:
    """‖L‖₁‖Lᴴ‖₁/‖A‖₁ growth of a (low-precision) Cholesky factor —
    the mixed rows' bound normalization (round 13, ROADMAP item 2):
    the refined solution's backward error is bounded through the
    LOW-precision factor's realized norms, so the denominator must
    carry them — a flat tol was blind to exactly the factor-precision
    loss the refinement has to recover."""
    l = np.tril(_np64(L))
    an = _np64(a)
    return max(1.0, np.linalg.norm(l, 1) * np.linalg.norm(l.conj().T, 1)
               / max(np.linalg.norm(an, 1), 1e-300))


def aasen_growth(LT, a) -> float:
    """‖L‖₁‖T‖₁‖L‖₁/‖A‖₁ growth of an Aasen LTLᴴ factor (T tridiagonal
    on the diag/subdiag, L multipliers shifted one column — the hetrs
    unpacking). Same role as :func:`lu_growth` for the hetrf/hesv rows
    (the round-5 on-chip sweep saw scaled error 7.62 at n=4096 pass
    only because tol was a flat 100)."""
    lt = _np64(LT)
    npad = lt.shape[0]
    strict = np.tril(lt, -2)
    lmat = np.pad(strict[:, :-1], ((0, 0), (1, 0))) + np.eye(npad)
    d = np.real(np.diagonal(lt))
    e = np.diagonal(lt, -1)
    t = np.diag(d.astype(lt.dtype)) + np.diag(e, -1) + np.diag(e.conj(), 1)
    an = _np64(a)
    nl = np.linalg.norm(lmat, 1)
    return max(1.0, nl * np.linalg.norm(t, 1) * nl
               / max(np.linalg.norm(an, 1), 1e-300))


# -- incremental-update budget (round 20 — ONE source of truth) -------------

# Default accumulated-update weight a resident factor absorbs before
# the Session schedules a counted refactor. Weight is Σ k·max(1, ‖W‖₁²/
# ‖A‖₁) over the updates applied since the last fresh factor — the
# count×growth form of the GGMS error accumulation (each rank-1 sweep
# adds O(u·‖W‖²/‖A‖) relative backward error, so small updates charge
# exactly their rank and large ones charge proportionally more).
DEFAULT_UPDATE_BUDGET = 64.0


def update_weight(k: int, wnorm1_sq: float, anorm1: float) -> float:
    """Accumulation charge of one rank-k update: k·max(1, ‖W‖₁²/‖A‖₁).
    Small deltas charge exactly k (the threshold-pin property tests
    rely on); deltas comparable to the operand itself charge more —
    they degrade conditioning faster than their rank suggests."""
    rel = wnorm1_sq / anorm1 if anorm1 > 0.0 else 0.0
    return float(k) * max(1.0, rel)


def update_refactor_due(count: int, weight: float, budget: float) -> bool:
    """Has the accumulated update weight exceeded the budget? The ONE
    predicate both the Session's update verb and the monitor's
    bookkeeping consult (ROADMAP item 2: update-count × growth bound
    decides, in obs/numerics — not scattered per caller). ``count`` is
    carried for observability/symmetry; weight ≥ count by construction
    so the budget bounds both."""
    del count
    return float(weight) > float(budget)


# -- Hager/Higham 1-norm estimation (the ?gecon / norm1est lineage) ---------


def norm1est(solve: Callable, solve_h: Callable, n: int,
             complex_: bool = False, max_iter: int = 5
             ) -> Tuple[float, int]:
    """Estimate ‖A⁻¹‖₁ given x ↦ A⁻¹x and x ↦ A⁻ᴴx as HOST callables
    (np [n, 1] in → np [n, 1]-compatible out; extra padded rows are
    sliced off). Returns ``(estimate, solves)`` — the solve count is
    what the Session's cost crediting charges.

    Complex-safe (Higham's complex variant): the 'sign' vector is
    y/|y| and iterates stay complex; ``solve_h`` must be the
    CONJUGATE-transpose solve (for Hermitian positive-definite
    operators A⁻ᴴ = A⁻¹, so one callable serves both — the pocondest
    convention). Finishes with Higham's alternating-ramp lower bound,
    exactly like linalg/condest (which adapts this loop for the eager
    drivers — one estimator, two seams)."""
    work = np.complex128 if complex_ else np.float64
    x = np.full((n, 1), 1.0 / n, dtype=work)
    est = 0.0
    solves = 0
    prev_sign = np.zeros((n, 1), dtype=work)
    for _ in range(max_iter):
        y = np.asarray(solve(x)).astype(work).reshape(-1, 1)[:n]
        solves += 1
        est = float(np.abs(y).sum())
        absy = np.abs(y)
        sign = np.where(absy == 0, 1.0, y / np.where(absy == 0, 1.0, absy))
        if (np.abs(sign - prev_sign) < 1e-12).all():
            break
        prev_sign = sign
        z = np.asarray(solve_h(sign)).astype(work).reshape(-1, 1)[:n]
        solves += 1
        j = int(np.argmax(np.abs(z)))
        if np.abs(z[j]).item() <= np.abs(np.conj(z).T @ x).item():
            break
        x = np.zeros((n, 1), dtype=work)
        x[j] = 1.0
    # alternative lower bound from a ramp vector (Higham's refinement)
    v = np.array([(-1.0) ** i * (1.0 + i / max(n - 1, 1))
                  for i in range(n)]).reshape(n, 1).astype(work)
    yv = np.asarray(solve(v)).astype(work).reshape(-1, 1)[:n]
    solves += 1
    alt = 2.0 * float(np.abs(yv).sum()) / (3.0 * n)
    return float(max(est, alt)), solves


def scaled_residual(rnorm: float, xnorm: float, bnorm: float,
                    anorm: float) -> float:
    """The probe's dimensionless backward-error proxy
    ‖b−Ax‖/(‖A‖·‖x‖+‖b‖) (max-norms; LAPACK's normwise relative
    residual family). NaN/Inf in any input propagates — the monitor's
    non-finite sentinel catches it."""
    den = float(anorm) * float(xnorm) + float(bnorm)
    if den == 0.0:
        return 0.0 if rnorm == 0.0 else float("inf")
    return float(rnorm) / den


# -- deterministic probe sampling -------------------------------------------

_PHI = (math.sqrt(5.0) - 1.0) / 2.0  # golden-ratio Weyl increment


class ResidualSampler:
    """Which served solves pay the residual probe: request i is probed
    iff frac(u₀ + i·φ) < fraction — a low-discrepancy Weyl sequence,
    so the probed share converges to ``fraction`` fast and the
    decision stream is a pure function of (seed, i) (the round-14
    reproducible-schedule discipline, applied to probing). ``decide``
    consumes the next index under a lock; ``peek(i)`` is the pure
    read tests pin determinism with."""

    def __init__(self, fraction: float = 0.0625, seed: int = 0):
        if not 0.0 <= fraction <= 1.0:
            raise ValueError("ResidualSampler: fraction must be in [0, 1]")
        self.fraction = float(fraction)
        self.seed = int(seed)
        # Knuth multiplicative hash of the seed -> u0 in [0, 1)
        self._u0 = ((self.seed * 2654435761) % (1 << 32)) / float(1 << 32)
        self._i = 0
        self._lock = threading.Lock()

    def peek(self, i: int) -> bool:
        return ((self._u0 + i * _PHI) % 1.0) < self.fraction

    def decide(self) -> bool:
        with self._lock:
            i = self._i
            self._i += 1
        return self.peek(i)

    @property
    def consumed(self) -> int:
        with self._lock:
            return self._i


# -- per-handle health state ------------------------------------------------


@dataclasses.dataclass
class NumericsConfig:
    """Thresholds and knobs for one monitor (all dimensionless — see
    module docstring).

    sample_fraction/seed  residual-probe sampling (ResidualSampler)
    condest_on_factor     run the condest probe after every (re)factor
                          of a supported operator (amortized like the
                          factor itself)
    growth_on_factor      growth bound from each fresh single-device
                          factor (host gather; mesh residents skip it —
                          condest is their factor-time signal)
    condest_max_iter      Hager iteration budget (LAPACK uses 5)
    ewma_alpha            residual / refine-iteration EWMA weight
    cond_*                u·κ̂ thresholds (u of the factor dtype for
                          refined residents): 0.1 means "κ within 10×
                          of the precision's breakdown point"
    resid_*               ρ/eps(working) thresholds
    growth_*              realized growth-factor thresholds
    refine_drift_degraded EWMA iters / best-seen-EWMA ratio that flags
                          conditioning drift on a refined handle
    """

    sample_fraction: float = 0.0625
    sample_seed: int = 0
    condest_on_factor: bool = True
    growth_on_factor: bool = True
    condest_max_iter: int = 5
    ewma_alpha: float = 0.25
    cond_degraded: float = 0.01
    cond_suspect: float = 0.1
    resid_degraded: float = 100.0
    resid_suspect: float = 1e5
    growth_degraded: float = 1e4
    growth_suspect: float = 1e8
    refine_drift_degraded: float = 4.0
    update_budget: float = DEFAULT_UPDATE_BUDGET


class _HandleStats:
    __slots__ = ("op", "work_dtype", "factor_dtype", "tenant",
                 "condest", "growth", "nonfinite",
                 "resid_ewma", "resid_last", "resid_max", "resid_count",
                 "refine_ewma", "refine_floor", "refine_count", "state",
                 "updates", "update_weight",
                 "gauge")

    def __init__(self):
        self.gauge = None  # last-published handle_health gauge name
        self.op = None
        self.work_dtype = None
        self.factor_dtype = None
        self.tenant = None
        self.condest = None
        self.growth = None
        self.nonfinite = 0
        self.resid_ewma = None
        self.resid_last = None
        self.resid_max = None
        self.resid_count = 0
        self.refine_ewma = None
        self.refine_floor = None
        self.refine_count = 0
        self.updates = 0
        self.update_weight = 0.0
        self.state = "healthy"


class NumericsMonitor:
    """Per-handle numerical-health state for one Session.

    The Session records signals at its existing seams (factor-time
    growth/condest, sampled solve-time residuals, per-solve refine
    iteration counts) guarded by ONE ``session.numerics is not None``
    check — the disabled path allocates nothing (the round-8
    discipline, extended here by test). Every record method returns
    ``(old_state, new_state)`` so the caller can run its reflex hooks
    on the transition; the monitor itself owns the gauges
    (``handle_health:{tenant}:{handle}`` — level 0/1/2 — plus the
    ``handles_degraded``/``handles_suspect`` aggregates) and the
    ``health_transitions_total`` counter on the bound Metrics.
    Thread-safe; jax-free."""

    def __init__(self, config: Optional[NumericsConfig] = None,
                 metrics=None, **kw):
        if config is not None and kw:
            # loud, not last-wins: silently dropping the kwargs would
            # let a drill believe it runs probe-every-solve while the
            # config object's default fraction actually applies
            raise ValueError(
                "NumericsMonitor: pass either a NumericsConfig or "
                f"field kwargs, not both (got config and {sorted(kw)})")
        self.config = config or NumericsConfig(**kw)
        self.metrics = metrics
        self.sampler = ResidualSampler(self.config.sample_fraction,
                                       self.config.sample_seed)
        self._lock = threading.Lock()
        self._handles: Dict[str, _HandleStats] = {}

    # -- recording seams ----------------------------------------------------

    def _stats(self, handle: Hashable) -> _HandleStats:
        h = repr(handle)
        s = self._handles.get(h)
        if s is None:
            s = self._handles[h] = _HandleStats()
        return s

    def record_factor(self, handle: Hashable, op: str, work_dtype: str,
                      factor_dtype: Optional[str] = None,
                      tenant: Optional[str] = None,
                      growth: Optional[float] = None,
                      finite: bool = True) -> Tuple[str, str]:
        """One fresh factor's signals: identity (op/dtypes/tenant — the
        eps the thresholds scale by), its realized growth bound (None =
        not computed, e.g. mesh residents), and the NaN/Inf sentinel."""
        with self._lock:
            s = self._stats(handle)
            s.op, s.work_dtype, s.tenant = op, str(work_dtype), tenant
            s.factor_dtype = (None if factor_dtype is None
                              else str(factor_dtype))
            bad = not finite
            if growth is not None:
                g = float(growth)
                s.growth = g
                bad = bad or not math.isfinite(g)
            if bad:
                # ONE event however it was reported (a non-finite
                # growth usually arrives with finite=False too) — the
                # per-handle count must agree with the session's
                # numerics_nonfinite_total event counter
                s.nonfinite += 1
            # a fresh factor zeroes the update-error accumulation — the
            # counted refactor is exactly what resets the GGMS budget
            s.updates = 0
            s.update_weight = 0.0
            return self._reclassify(handle, s)

    def record_update(self, handle: Hashable, k: int, weight: float
                      ) -> Tuple[str, str]:
        """One applied rank-k incremental update (round 20): accrue
        its accumulation charge (:func:`update_weight`) toward the
        handle's budget. Whether the accrued total now demands a
        refactor is read via :meth:`update_due` — the Session's update
        verb consults it AFTER recording, so the update that crosses
        the budget is still served and the refactor runs off the
        answer path."""
        with self._lock:
            s = self._stats(handle)
            s.updates += 1
            s.update_weight += float(weight)
            if not math.isfinite(s.update_weight):
                s.nonfinite += 1
            return self._reclassify(handle, s)

    def update_due(self, handle: Hashable) -> bool:
        """Has ``handle`` accumulated enough update weight to owe a
        refactor? (:func:`update_refactor_due` against the config's
        budget — the one predicate.)"""
        with self._lock:
            s = self._handles.get(repr(handle))
            if s is None:
                return False
            return update_refactor_due(s.updates, s.update_weight,
                                       self.config.update_budget)

    def record_condest(self, handle: Hashable, cond: float
                       ) -> Tuple[str, str]:
        with self._lock:
            s = self._stats(handle)
            c = float(cond)
            s.condest = c
            if not math.isfinite(c):
                s.nonfinite += 1
            return self._reclassify(handle, s)

    def record_residual(self, handle: Hashable, rho: float,
                        work_dtype: Optional[str] = None
                        ) -> Tuple[str, str]:
        """One sampled probe's scaled residual ρ. ``work_dtype`` seeds
        the eps the thresholds scale by when the probe precedes the
        first record_factor (the late-enable warm-cache path —
        without it the float64-eps fallback would flag an f32
        handle's perfectly healthy residuals suspect)."""
        with self._lock:
            s = self._stats(handle)
            if s.work_dtype is None and work_dtype is not None:
                s.work_dtype = str(work_dtype)
            r = float(rho)
            s.resid_last = r
            s.resid_count += 1
            if not math.isfinite(r):
                s.nonfinite += 1
            else:
                a = self.config.ewma_alpha
                s.resid_ewma = (r if s.resid_ewma is None
                                else (1.0 - a) * s.resid_ewma + a * r)
                s.resid_max = (r if s.resid_max is None
                               else max(s.resid_max, r))
            return self._reclassify(handle, s)

    def record_refine(self, handle: Hashable, iters: int
                      ) -> Tuple[str, str]:
        """One refined solve's iteration count — drift of the EWMA
        above its best-seen floor is the conditioning-degradation
        proxy (more iterations to reach the same tolerance means
        u_f·κ grew, Carson & Higham's contraction factor)."""
        with self._lock:
            s = self._stats(handle)
            it = float(iters)
            s.refine_count += 1
            a = self.config.ewma_alpha
            s.refine_ewma = (it if s.refine_ewma is None
                             else (1.0 - a) * s.refine_ewma + a * it)
            s.refine_floor = (s.refine_ewma if s.refine_floor is None
                              else min(s.refine_floor, s.refine_ewma))
            return self._reclassify(handle, s)

    # -- classification -----------------------------------------------------

    def _classify(self, s: _HandleStats) -> str:
        cfg = self.config
        if s.nonfinite:
            return "suspect"
        level = 0
        if s.condest is not None:
            # u of the factor dtype for refined residents — the
            # precision the resident actually lives in
            u = dtype_eps(s.factor_dtype or s.work_dtype)
            ucond = s.condest * u
            if ucond > cfg.cond_suspect:
                level = max(level, 2)
            elif ucond > cfg.cond_degraded:
                level = max(level, 1)
        if s.growth is not None:
            if s.growth > cfg.growth_suspect:
                level = max(level, 2)
            elif s.growth > cfg.growth_degraded:
                level = max(level, 1)
        if s.resid_ewma is not None:
            eps = dtype_eps(s.work_dtype)
            if s.resid_ewma > cfg.resid_suspect * eps:
                level = max(level, 2)
            elif s.resid_ewma > cfg.resid_degraded * eps:
                level = max(level, 1)
        if (s.refine_ewma is not None and s.refine_floor
                and s.refine_ewma
                > cfg.refine_drift_degraded * s.refine_floor):
            level = max(level, 1)
        return HEALTH_STATES[level]

    def _reclassify(self, handle: Hashable, s: _HandleStats
                    ) -> Tuple[str, str]:
        """Caller holds the lock. Recompute the state, publish the
        gauge, count/log the transition."""
        old, new = s.state, self._classify(s)
        s.state = new
        m = self.metrics
        if m is not None:
            tname = s.tenant if s.tenant is not None else "default"
            gname = f"handle_health:{tname}:{repr(handle)}"
            if s.gauge is not None and s.gauge != gname:
                # the tenant was learned after the first record (a
                # warm-cache probe precedes record_factor on the
                # late-enable path): drop the provisional gauge so
                # relabeling cannot leak a stale /metrics row
                m.drop_gauge(s.gauge)
            s.gauge = gname
            m.set_gauge(gname, float(_LEVEL[new]))
        if new != old:
            counts = self._counts_locked()
            if m is not None:
                m.inc("health_transitions_total")
                m.set_gauge("handles_degraded",
                            float(counts.get("degraded", 0)))
                m.set_gauge("handles_suspect",
                            float(counts.get("suspect", 0)))
            (log.warning if _LEVEL[new] > _LEVEL[old] else log.info)(
                "numerics: handle %r health %s -> %s (condest=%s, "
                "growth=%s, resid_ewma=%s, nonfinite=%d)", handle, old,
                new, s.condest, s.growth, s.resid_ewma, s.nonfinite)
        return old, new

    # -- reads --------------------------------------------------------------

    def health(self, handle: Hashable) -> Optional[str]:
        with self._lock:
            s = self._handles.get(repr(handle))
            return None if s is None else s.state

    def placement_info(self, handle: Hashable
                       ) -> Tuple[Optional[str], Optional[float],
                                  Optional[float]]:
        """(health, condest, growth) for one placement-snapshot row —
        (None, None, None) for untracked handles (the disabled-path
        columns)."""
        with self._lock:
            s = self._handles.get(repr(handle))
            if s is None:
                return None, None, None
            return s.state, s.condest, s.growth

    def _counts_locked(self) -> Dict[str, int]:
        counts = {s: 0 for s in HEALTH_STATES}
        for st in self._handles.values():
            counts[st.state] += 1
        return counts

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return self._counts_locked()

    def snapshot(self) -> dict:
        """The ``/numerics`` payload: per-handle signal rows + the
        state histogram + the config (so a scrape is self-describing)."""
        with self._lock:
            handles = {
                h: {
                    "op": s.op, "work_dtype": s.work_dtype,
                    "factor_dtype": s.factor_dtype, "tenant": s.tenant,
                    "condest": s.condest, "growth": s.growth,
                    "nonfinite": s.nonfinite,
                    "resid_ewma": s.resid_ewma,
                    "resid_last": s.resid_last,
                    "resid_max": s.resid_max,
                    "resid_count": s.resid_count,
                    "refine_ewma": s.refine_ewma,
                    "refine_count": s.refine_count,
                    "updates": s.updates,
                    "update_weight": s.update_weight,
                    "state": s.state,
                }
                for h, s in self._handles.items()
            }
            counts = self._counts_locked()
            probes = self.sampler.consumed
        return {
            "schema": "slate_tpu.numerics.v1",
            "handles": handles,
            "counts": counts,
            "sampler_decisions": probes,
            "config": dataclasses.asdict(self.config),
        }

    # -- checkpoint carryover (round 17) ------------------------------------

    # every _HandleStats field a checkpoint record round-trips (gauge
    # is rebuilt at import; state is re-derived and pinned equal)
    _EXPORT_FIELDS = ("op", "work_dtype", "factor_dtype", "tenant",
                      "condest", "growth", "nonfinite", "resid_ewma",
                      "resid_last", "resid_max", "resid_count",
                      "refine_ewma", "refine_floor", "refine_count",
                      "updates", "update_weight",
                      "state")

    def export_state(self, handle: Hashable) -> Optional[dict]:
        """One handle's full signal state for a checkpoint record —
        classification is a pure function of these fields, so a
        restored handle re-derives the SAME health state (a suspect
        handle stays suspect across the restart, the round-17
        carryover pin). None for untracked handles."""
        with self._lock:
            s = self._handles.get(repr(handle))
            if s is None:
                return None
            return {k: getattr(s, k) for k in self._EXPORT_FIELDS}

    def import_state(self, handle: Hashable, d: dict) -> Tuple[str, str]:
        """Seed a handle's signal state from a checkpoint record
        (round-17 restore). The state is re-derived from the imported
        signals through the normal classifier — when it agrees with
        the recorded state (it always does for an unedited record; the
        classifier is pure) no transition is counted; a hand-edited or
        schema-drifted record that disagrees logs the transition like
        any live signal would. Publishes the handle_health gauge."""
        with self._lock:
            s = self._stats(handle)
            for k in self._EXPORT_FIELDS:
                if k in d and d[k] is not None:
                    setattr(s, k, d[k])
            s.nonfinite = int(d.get("nonfinite", 0) or 0)
            s.state = str(d.get("state", "healthy"))
            return self._reclassify(handle, s)

    def forget(self, handle: Hashable):
        """Drop a handle's row and gauge (unregister — the round-15
        churn-cardinality discipline); counters keep their history."""
        with self._lock:
            s = self._handles.pop(repr(handle), None)
            if s is not None and self.metrics is not None:
                if s.gauge is not None:
                    self.metrics.drop_gauge(s.gauge)
                counts = self._counts_locked()
                self.metrics.set_gauge(
                    "handles_degraded", float(counts.get("degraded", 0)))
                self.metrics.set_gauge(
                    "handles_suspect", float(counts.get("suspect", 0)))
