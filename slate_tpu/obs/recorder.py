"""Flight recorder + decision journal: black-box capture for serving.

Three always-on, bounded, stdlib-only rings behind ONE opt-in object
(:class:`Recorder`), extending the round-8 hot-path discipline: every
runtime seam guards with a single ``recorder is None`` check — the
disabled path allocates nothing and calls nothing in this module
(pinned by test).

* :class:`DecisionJournal` — every reflex decision (shed, breaker
  transition, eviction, failover rung, tuner promotion, ...) recorded
  as ONE :class:`~slate_tpu.obs.events.DecisionEvent` with the inputs
  that drove it. Per-kind counts are maintained monotonically OUTSIDE
  the ring, so the parity invariant (journal count == metric counter
  delta, :data:`~slate_tpu.obs.events.KIND_COUNTERS`) survives ring
  eviction.
* :class:`FlightRecorder` — recent finished spans (fed by the Tracer's
  ``recorder`` hook on span finish) plus throttled backpressure/gauge +
  stage-histogram samples: the last seconds of *how the system felt*,
  cheap enough to leave on.
* :class:`IncidentCapture` — anomaly/breach/breaker/fault triggers
  materialize a rate-limited, deduped ``slate_tpu.incident.v1``
  snapshot: the recent journal slice, the flight rings, a metrics
  snapshot, and whatever providers the session wired (numerics health,
  quota state, placement rows, cost_log + tuning provenance for the
  implicated handles) — written crash-safe (tmp + ``os.replace``, the
  round-17 atomic-publish discipline) under a configurable dir and
  kept in a memory ring for the ``/incidents`` route.

The fleet story lives in :mod:`.aggregate`
(``merge_journal_payloads`` / ``merge_incident_payloads``): N
processes' journals fold into one host-labeled timeline with exact
count conservation.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from .events import (DecisionEvent, INCIDENT_SCHEMA, JOURNAL_SCHEMA,
                     journal_digest, validate_incident)

__all__ = ["DecisionJournal", "FlightRecorder", "IncidentCapture",
           "Recorder", "validate_incident"]

_SAFE = re.compile(r"[^A-Za-z0-9_.-]+")


class DecisionJournal:
    """Thread-safe bounded ring of :class:`DecisionEvent` rows plus
    monotone per-kind / per-(kind, outcome) count tables (class
    docstring above for why the counts live outside the ring)."""

    def __init__(self, capacity: int = 1024):
        self.capacity = int(capacity)
        self._ring: "deque[DecisionEvent]" = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self._counts: Dict[str, float] = {}
        self._outcome_counts: Dict[str, float] = {}

    def record(self, kind: str, *, op=None, handle=None, tenant=None,
               inputs: Optional[dict] = None, outcome=None,
               count: float = 1.0, trace_id=None, span_id=None,
               ts: Optional[float] = None) -> DecisionEvent:
        c = float(count)
        with self._lock:
            self._seq += 1
            ev = DecisionEvent(
                seq=self._seq,
                ts=time.time() if ts is None else ts,
                kind=kind,
                op=None if op is None else str(op),
                handle=None if handle is None else str(handle),
                tenant=None if tenant is None else str(tenant),
                inputs=inputs, outcome=outcome, count=c,
                trace_id=trace_id, span_id=span_id)
            self._ring.append(ev)
            self._counts[kind] = self._counts.get(kind, 0.0) + c
            if outcome is not None:
                k = f"{kind}:{outcome}"
                self._outcome_counts[k] = \
                    self._outcome_counts.get(k, 0.0) + c
        return ev

    # -- reads ---------------------------------------------------------------

    def events(self, limit: Optional[int] = None, kind=None,
               handle=None) -> List[DecisionEvent]:
        """Snapshot (oldest first), optionally filtered/tail-limited."""
        with self._lock:
            rows = list(self._ring)
        if kind is not None:
            rows = [e for e in rows if e.kind == kind]
        if handle is not None:
            h = str(handle)
            rows = [e for e in rows if e.handle == h]
        if limit is not None:
            rows = rows[-int(limit):]
        return rows

    def count(self, kind: str) -> float:
        with self._lock:
            return self._counts.get(kind, 0.0)

    def counts(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._counts)

    def outcome_count(self, kind: str, outcome: str) -> float:
        with self._lock:
            return self._outcome_counts.get(f"{kind}:{outcome}", 0.0)

    def outcome_counts(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._outcome_counts)

    def digest(self) -> str:
        """Deterministic-field digest of the ring (events.py)."""
        return journal_digest(self.events())

    def payload(self) -> dict:
        """The ``/journal`` route document."""
        with self._lock:
            rows = [e.to_dict() for e in self._ring]
            recorded = self._seq
            counts = dict(self._counts)
            outcome_counts = dict(self._outcome_counts)
        return {
            "schema": JOURNAL_SCHEMA,
            "capacity": self.capacity,
            "recorded": recorded,
            "dropped": recorded - len(rows),
            "counts": counts,
            "outcome_counts": outcome_counts,
            "events": rows,
        }


class FlightRecorder:
    """Bounded rings of recent finished spans and throttled gauge/
    stage-histogram samples (module docstring)."""

    def __init__(self, span_capacity: int = 256,
                 sample_capacity: int = 64,
                 sample_interval_s: float = 0.25,
                 clock: Callable[[], float] = time.time):
        self._spans: "deque[dict]" = deque(maxlen=int(span_capacity))
        self._samples: "deque[dict]" = deque(maxlen=int(sample_capacity))
        self.sample_interval_s = float(sample_interval_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._last_sample = 0.0

    def record_span(self, span) -> None:
        """Tracer ``finish_span`` hook: one finished span into the
        ring (duck-typed on the Span fields; never raises into the
        tracer)."""
        try:
            end = span.end
            row = {
                "ts": self._clock(), "name": span.name,
                "kind": span.kind, "trace_id": span.trace_id,
                "span_id": span.span_id, "status": span.status,
                "dur_s": (end - span.start) if end is not None else None,
            }
        except Exception:
            return
        with self._lock:
            self._spans.append(row)

    def sample(self, metrics, now: Optional[float] = None) -> dict:
        """One backpressure sample: every gauge plus the lifecycle
        ``stage_*`` histogram snapshots."""
        now = self._clock() if now is None else now
        snap = metrics.snapshot()
        row = {
            "ts": now,
            "gauges": snap.get("gauges", {}),
            "stages": {k: v for k, v in snap.get("histograms",
                                                 {}).items()
                       if k.startswith("stage_")},
        }
        with self._lock:
            self._samples.append(row)
            self._last_sample = now
        return row

    def maybe_sample(self, metrics) -> Optional[dict]:
        """Throttled :meth:`sample` (at most one per interval) — the
        journal calls this on every decision, so the sample ring
        tracks exactly the windows where the system was deciding
        things, without hot-loop cost."""
        now = self._clock()
        with self._lock:
            if now - self._last_sample < self.sample_interval_s:
                return None
        return self.sample(metrics, now)

    def payload(self) -> dict:
        with self._lock:
            return {"spans": list(self._spans),
                    "samples": list(self._samples)}


class IncidentCapture:
    """Rate-limited, deduped materialization of incident snapshots
    (module docstring). ``providers`` maps section name -> zero-arg
    callable; every provider failure is captured as an error string,
    never raised into the triggering seam."""

    def __init__(self, journal: DecisionJournal, flight: FlightRecorder,
                 dir: Optional[str] = None, rate_limit_s: float = 5.0,
                 dedup_window_s: float = 60.0, capacity: int = 32,
                 journal_slice: int = 64, host: Optional[str] = None,
                 metrics=None, clock: Callable[[], float] = time.time):
        self.journal = journal
        self.flight = flight
        self.dir = dir
        self.rate_limit_s = float(rate_limit_s)
        self.dedup_window_s = float(dedup_window_s)
        self.journal_slice = int(journal_slice)
        self.host = host or f"pid{os.getpid()}"
        self.metrics = metrics
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: "deque[dict]" = deque(maxlen=int(capacity))
        self._seq = 0
        self._last_capture = None          # ts of last capture (any)
        self._last_by_key: Dict[str, float] = {}
        self.providers: Dict[str, Callable[[], object]] = {}

    # -- the trigger ---------------------------------------------------------

    def trigger(self, reason: str, key=None,
                context: Optional[dict] = None,
                handle=None) -> Optional[dict]:
        """One anomalous transition. Returns the captured incident
        document, or None when deduped / rate-limited (counted either
        way on the attached metrics)."""
        now = self._clock()
        dedup_key = f"{reason}:{key}"
        with self._lock:
            seen = self._last_by_key.get(dedup_key)
            if seen is not None and now - seen < self.dedup_window_s:
                if self.metrics is not None:
                    self.metrics.inc("incidents_deduped_total")
                return None
            if (self._last_capture is not None
                    and now - self._last_capture < self.rate_limit_s):
                if self.metrics is not None:
                    self.metrics.inc("incidents_rate_limited_total")
                return None
            self._seq += 1
            seq = self._seq
            self._last_capture = now
            self._last_by_key[dedup_key] = now
        doc = self._capture(seq, now, reason, key, context, handle)
        with self._lock:
            self._ring.append(doc)
        if self.metrics is not None:
            self.metrics.inc("incidents_captured_total")
        if self.dir is not None:
            self._publish(doc)
        return doc

    # -- capture -------------------------------------------------------------

    def _section(self, name: str):
        fn = self.providers.get(name)
        if fn is None:
            return None
        try:
            return fn()
        except Exception as e:  # never fail the triggering seam
            return {"error": f"{type(e).__name__}: {e}"}

    def _capture(self, seq, now, reason, key, context, handle) -> dict:
        events = self.journal.events(limit=self.journal_slice)
        if handle is not None:
            # the implicated handle's slice rides along even when the
            # tail window is dominated by other traffic
            h = str(handle)
            tail_seqs = {e.seq for e in events}
            events = ([e for e in self.journal.events(handle=h)
                       if e.seq not in tail_seqs] + events)
            events.sort(key=lambda e: e.seq)
        metrics_snap = self._section("metrics") or {"counters": {},
                                                    "gauges": {}}
        return {
            "schema": INCIDENT_SCHEMA,
            "id": f"inc-{seq:04d}-{_SAFE.sub('_', str(reason))}",
            "ts": now,
            "host": self.host,
            "reason": str(reason),
            "key": None if key is None else str(key),
            "context": dict(context) if context else {},
            "journal": {
                "events": [e.to_dict() for e in events],
                "counts": self.journal.counts(),
                "outcome_counts": self.journal.outcome_counts(),
            },
            "flight": self.flight.payload(),
            "metrics": {
                "counters": metrics_snap.get("counters", {}),
                "gauges": metrics_snap.get("gauges", {}),
            },
            "numerics": self._section("numerics"),
            "quotas": self._section("quotas"),
            "placement": self._section("placement"),
            "cost_log": self._section("cost_log"),
            "tuning": self._section("tuning"),
        }

    def _publish(self, doc: dict) -> None:
        """Crash-safe single-file publish: write sibling tmp, fsync,
        ``os.replace`` (a reader never sees a torn incident)."""
        try:
            os.makedirs(self.dir, exist_ok=True)
            path = os.path.join(self.dir, f"{doc['id']}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=2, sort_keys=True)
                f.write("\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError:
            if self.metrics is not None:
                self.metrics.inc("incident_write_errors_total")

    # -- reads ---------------------------------------------------------------

    def incidents(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def payload(self) -> dict:
        """The ``/incidents`` route document."""
        with self._lock:
            rows = list(self._ring)
            captured = self._seq
        return {
            "schema": "slate_tpu.incidents.v1",
            "host": self.host,
            "captured": captured,
            "dir": self.dir,
            "incidents": rows,
        }


class Recorder:
    """The facade the runtime seams hold: one journal, one flight
    recorder, one incident capture. ``session.recorder`` (and
    ``fleet.recorder``) default to None; every seam guards with one
    is-None check (module docstring)."""

    def __init__(self, journal_capacity: int = 1024,
                 flight_spans: int = 256, flight_samples: int = 64,
                 incident_dir: Optional[str] = None,
                 rate_limit_s: float = 5.0,
                 dedup_window_s: float = 60.0,
                 incident_capacity: int = 32,
                 journal_slice: int = 64,
                 host: Optional[str] = None,
                 metrics=None, tracer=None,
                 clock: Callable[[], float] = time.time):
        self.metrics = metrics
        self.tracer = tracer
        self.journal = DecisionJournal(capacity=journal_capacity)
        self.flight = FlightRecorder(span_capacity=flight_spans,
                                     sample_capacity=flight_samples,
                                     clock=clock)
        self.incidents = IncidentCapture(
            self.journal, self.flight, dir=incident_dir,
            rate_limit_s=rate_limit_s, dedup_window_s=dedup_window_s,
            capacity=incident_capacity, journal_slice=journal_slice,
            host=host, metrics=metrics, clock=clock)
        self.providers = self.incidents.providers  # one wiring surface

    # -- seam entry points ---------------------------------------------------

    def decision(self, kind: str, *, op=None, handle=None, tenant=None,
                 inputs: Optional[dict] = None, outcome=None,
                 count: float = 1.0) -> DecisionEvent:
        """Record one reflex decision (joined to the current span when
        a tracer rides along) and opportunistically refresh the
        backpressure sample ring."""
        trace_id = span_id = None
        t = self.tracer
        if t is not None and t.enabled:
            cur = t.current()
            if cur is not None:
                trace_id, span_id = cur.trace_id, cur.span_id
        ev = self.journal.record(kind, op=op, handle=handle,
                                 tenant=tenant, inputs=inputs,
                                 outcome=outcome, count=count,
                                 trace_id=trace_id, span_id=span_id)
        if self.metrics is not None:
            self.flight.maybe_sample(self.metrics)
        return ev

    def incident(self, reason: str, key=None,
                 context: Optional[dict] = None,
                 handle=None) -> Optional[dict]:
        if self.metrics is not None:
            self.flight.maybe_sample(self.metrics)
        return self.incidents.trigger(reason, key=key, context=context,
                                      handle=handle)

    # -- hooks ---------------------------------------------------------------

    def watchdog_listener(self, row: dict) -> None:
        """``Watchdog.add_listener`` target: every anomaly row is an
        incident trigger (the watchdog already emits only on ok ->
        anomalous transitions, so scrape loops cannot restorm this)."""
        self.incident("watchdog_anomaly",
                      key=row.get("series") or row.get("metric"),
                      context=row)

    def span_finished(self, span) -> None:
        """Tracer hook (``tracer.recorder``): finished spans feed the
        flight ring."""
        self.flight.record_span(span)
