"""Metrics exposition: Prometheus text format + stdlib HTTP endpoint.

``render_prometheus`` turns a ``runtime.Metrics`` snapshot (counters +
histograms + derived gauges) and the process FLOP ledger into the
Prometheus text exposition format (version 0.0.4 — the format every
fleet scraper ingests). ``ObsServer`` is the opt-in serving endpoint:
a stdlib-only (http.server) threaded listener with

* ``GET /metrics``    — Prometheus text of the bound Metrics + ledger
* ``GET /healthz``    — liveness JSON ({"status": "ok", uptime, ...})
* ``GET /trace.json`` — Chrome-trace JSON of the bound Tracer's spans
* ``GET /slo``        — SLO burn-rate payload (obs.slo.SloTracker
  .evaluate; {"enabled": false} when no tracker is bound)
* ``GET /tenants``    — tenant attribution + placement payload
  (round 15: per-(tenant, handle) counter cells, handle heat, the
  placement snapshot; {"enabled": false} when no ledger is bound)
* ``GET /numerics``   — numerical-health payload (round 16:
  per-handle condest/growth/residual signals and the
  healthy/degraded/suspect states; {"enabled": false} when no
  monitor is bound)
* ``GET /history``    — the time-series store payload (round 23:
  per-series raw rings + downsample tiers; ``?series=a,b`` filters;
  {"enabled": false} when no store is bound — /metrics stays
  instantaneous, history is JSON-only)
* ``GET /forecast``   — per-series trend/seasonality forecasts,
  predicted-hot ranking, exhaustion runways (``?horizon_s=`` tunes
  the horizon; {"enabled": false} when no forecaster is bound)

No third-party dependency, daemon threads only, ephemeral port by
default (``port=0``) so tests and co-located sessions never collide.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlsplit

from . import flops as flops_mod
from .export import chrome_trace

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _san(name: str) -> str:
    return _NAME_RE.sub("_", name)


def render_prometheus(snapshot, prefix: str = "slate_tpu",
                      ledger: Optional["flops_mod.FlopLedger"] = None,
                      bytes_ledger=None, attribution=None,
                      quotas=None) -> str:
    """Metrics snapshot (or a Metrics instance) -> Prometheus text.

    Counters render as ``counter``; histograms as ``summary`` (count,
    sum, p50/p99 quantiles) with ``_min``/``_max`` gauges beside them
    (omitted while empty — see Histogram.snapshot's null contract);
    derived ratios and explicit gauges as ``gauge`` — the Session's
    HBM truth is the round-11 per-chip vocabulary: ``resident_bytes``
    / ``peak_hbm_bytes`` / ``hbm_headroom`` are PER-CHIP numbers
    (max-per-shard charge for mesh residents) and
    ``resident_bytes_total`` is the aggregate across the mesh; the
    ``solve_collective_bytes_total`` / ``factor_collective_bytes_total``
    counters split the served ICI traffic per verb. ``ledger=None`` binds the process flop
    ledger and ``bytes_ledger=None`` the process bytes ledger
    (``driver_bytes_total`` / ``collective_bytes_total`` — round 9);
    pass either ``False`` to disable its section.

    ``attribution`` (round 15): an
    :class:`~.attribution.AttributionLedger` or its ``snapshot()``
    dict — renders the ``tenant_*`` sections (one
    ``{prefix}_tenant_<class>_total{{tenant="..."}}`` counter row per
    tenant per counter class, plus a ``tenant_handles`` gauge); the
    per-(tenant, handle) cells stay in the JSON payload (/tenants) —
    handle-level Prometheus label cardinality is the scrape-killer
    the per-tenant rollup exists to avoid. None = no section (the
    default: a session without attribution renders exactly what it
    rendered before).

    ``quotas`` (round 18): a ``Session.quotas_payload()`` dict —
    renders tenant-LABELED quota rows
    (``{prefix}_tenant_quota_resident_bytes{{tenant="..."}}`` and the
    declared sub-budget) beside the name-mangled per-tenant gauges
    the Session already publishes; same rollup-only cardinality
    discipline. None/disabled = no section."""
    if hasattr(snapshot, "snapshot"):
        snapshot = snapshot.snapshot()
    if ledger is None:
        ledger = flops_mod.LEDGER
    elif not ledger:  # explicit falsy (False/0): no ledger section
        ledger = None
    if bytes_ledger is None:
        from . import costs as costs_mod
        bytes_ledger = costs_mod.BYTES
    elif not bytes_ledger:
        bytes_ledger = None
    lines = []

    def emit(name, value, mtype=None, labels=""):
        if mtype:
            lines.append(f"# TYPE {name} {mtype}")
        lines.append(f"{name}{labels} {_num(value)}")

    emit(f"{prefix}_uptime_seconds", snapshot.get("uptime_s", 0.0), "gauge")
    for k in sorted(snapshot.get("counters", {})):
        emit(f"{prefix}_{_san(k)}", snapshot["counters"][k], "counter")
    for k in sorted(snapshot.get("histograms", {})):
        h = snapshot["histograms"][k]
        base = f"{prefix}_{_san(k)}"
        lines.append(f"# TYPE {base} summary")
        lines.append(f'{base}{{quantile="0.5"}} {_num(h.get("p50", 0.0))}')
        lines.append(f'{base}{{quantile="0.99"}} {_num(h.get("p99", 0.0))}')
        lines.append(f"{base}_sum {_num(h.get('sum', 0.0))}")
        lines.append(f"{base}_count {_num(h.get('count', 0))}")
        # min/max are None for an empty histogram (indistinguishability
        # fix, runtime/metrics.py) — omit rather than fake a 0.0
        for stat in ("min", "max", "mean"):
            v = h.get(stat)
            if v is not None:
                emit(f"{base}_{stat}", v, "gauge")
        # round 12: the worst observation's exemplar trace-id (set by
        # the lifecycle-stage histograms) as a plain gauge — the 0.0.4
        # text format has no exemplar syntax, and a trace id is a
        # join key, not a measurement
        ex = h.get("exemplar")
        if ex and ex.get("trace_id") is not None:
            emit(f"{base}_exemplar_trace_id", ex["trace_id"], "gauge")
    for k in sorted(snapshot.get("gauges", {})):
        emit(f"{prefix}_{_san(k)}", snapshot["gauges"][k], "gauge")
    for k in sorted(snapshot.get("derived", {})):
        emit(f"{prefix}_{_san(k)}", snapshot["derived"][k], "gauge")
    if ledger is not None:
        snap = ledger.snapshot()
        emit(f"{prefix}_driver_flops_total", snap["flops_total"], "counter")
        if snap["per_op"]:
            lines.append(f"# TYPE {prefix}_driver_flops counter")
            for op in sorted(snap["per_op"]):
                lines.append(f'{prefix}_driver_flops{{op="{_san(op)}"}} '
                             f'{_num(snap["per_op"][op])}')
    if bytes_ledger is not None:
        # the round-9 bytes/communication section: XLA bytes-accessed
        # and modeled collective (ICI) traffic, per op and per kind
        bsnap = bytes_ledger.snapshot()
        emit(f"{prefix}_driver_bytes_total", bsnap["bytes_total"],
             "counter")
        emit(f"{prefix}_collective_bytes_total",
             bsnap["collective_bytes_total"], "counter")
        if bsnap["per_op"]:
            lines.append(f"# TYPE {prefix}_driver_bytes counter")
            for op in sorted(bsnap["per_op"]):
                lines.append(
                    f'{prefix}_driver_bytes{{op="{_san(op)}"}} '
                    f'{_num(bsnap["per_op"][op]["bytes"])}')
        if bsnap["per_collective"]:
            lines.append(f"# TYPE {prefix}_collective_bytes counter")
            lines.append(f"# TYPE {prefix}_collective_ops_total counter")
            for kind in sorted(bsnap["per_collective"]):
                row = bsnap["per_collective"][kind]
                lines.append(
                    f'{prefix}_collective_bytes{{kind="{_san(kind)}"}} '
                    f'{_num(row["bytes"])}')
                lines.append(
                    f'{prefix}_collective_ops_total{{kind="{_san(kind)}"}}'
                    f' {_num(row["count"])}')
    if attribution is not None:
        lines.extend(render_tenant_sections(attribution, prefix=prefix))
    if quotas:
        lines.extend(render_quota_sections(quotas, prefix=prefix))
    return "\n".join(lines) + "\n"


def render_quota_sections(quotas: dict, prefix: str = "slate_tpu"
                          ) -> list:
    """The tenant-labeled quota rows of a ``quotas_payload()`` dict
    (round 18): live resident bytes and (where declared) the
    sub-budget per tenant. Shared shape with the fleet renderer's
    ``fleet_tenant_quota_*`` rows so the two surfaces cannot drift.
    Empty when the payload is absent/disabled."""
    if not isinstance(quotas, dict) or not quotas.get("enabled"):
        return []
    lines = []
    tenants = quotas.get("tenants", {})
    if tenants:
        lines.append(
            f"# TYPE {prefix}_tenant_quota_resident_bytes gauge")
        for tenant in sorted(tenants):
            row = tenants[tenant]
            lines.append(
                f'{prefix}_tenant_quota_resident_bytes'
                f'{{tenant="{_san(tenant)}"}} '
                f"{_num(row.get('resident_bytes', 0))}")
            if row.get("max_resident_bytes") is not None:
                lines.append(
                    f'{prefix}_tenant_quota_max_resident_bytes'
                    f'{{tenant="{_san(tenant)}"}} '
                    f"{_num(row['max_resident_bytes'])}")
    return lines


def render_tenant_sections(attribution, prefix: str = "slate_tpu"
                           ) -> list:
    """The ``tenant_*`` Prometheus lines of an attribution snapshot
    (or ledger): per-tenant counter rollups per class, one
    ``tenant_handles`` gauge per tenant. Shared by the single-process
    /metrics route and the fleet renderer (aggregate.py), so the two
    surfaces cannot drift."""
    if hasattr(attribution, "snapshot"):
        attribution = attribution.snapshot()
    lines = []
    tenants = attribution.get("tenants", {})
    if not tenants:
        return lines
    classes = sorted({cls for t in tenants.values()
                      for cls in t.get("totals", {})})
    for cls in classes:
        name = f"{prefix}_tenant_{_san(cls)}_total"
        lines.append(f"# TYPE {name} counter")
        for tenant in sorted(tenants):
            v = tenants[tenant].get("totals", {}).get(cls)
            if v is not None:
                lines.append(
                    f'{name}{{tenant="{_san(tenant)}"}} {_num(v)}')
    lines.append(f"# TYPE {prefix}_tenant_handles gauge")
    for tenant in sorted(tenants):
        lines.append(
            f'{prefix}_tenant_handles{{tenant="{_san(tenant)}"}} '
            f'{_num(len(tenants[tenant].get("handles", {})))}')
    return lines


def _num(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


class _Handler(BaseHTTPRequestHandler):
    # the bound ObsServer is attached to the server object

    def do_GET(self):  # noqa: N802 — http.server API
        obs: "ObsServer" = self.server.obs  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            attr = (obs.attribution() if callable(obs.attribution)
                    else obs.attribution)
            quotas = (obs.quotas() if callable(obs.quotas)
                      else obs.quotas)
            body = render_prometheus(obs.metrics, ledger=obs.ledger,
                                     attribution=attr, quotas=quotas)
            self._reply(200, body, "text/plain; version=0.0.4")
        elif path == "/healthz":
            snap = obs.metrics.snapshot()
            body = json.dumps({
                "status": "ok",
                "uptime_s": snap.get("uptime_s", 0.0),
                "solves_total": snap.get("counters", {}).get(
                    "solves_total", 0.0),
                "tracing": bool(obs.tracer is not None
                                and obs.tracer.enabled),
            }) + "\n"
            self._reply(200, body, "application/json")
        elif path == "/trace.json":
            spans = obs.tracer.spans() if obs.tracer is not None else []
            body = json.dumps(chrome_trace(spans)) + "\n"
            self._reply(200, body, "application/json")
        elif path == "/slo":
            tracker = obs.slo() if callable(obs.slo) else obs.slo
            payload = (tracker.evaluate() if tracker is not None
                       else {"enabled": False, "objectives": []})
            body = json.dumps(payload) + "\n"
            self._reply(200, body, "application/json")
        elif path == "/numerics":
            # round 16: the numerical-health payload (getter-bound so
            # a monitor enabled AFTER the server started is served —
            # the /slo provider discipline)
            payload = (obs.numerics() if callable(obs.numerics)
                       else obs.numerics)
            if payload is None:
                payload = {"enabled": False, "handles": {}}
            body = json.dumps(payload, sort_keys=True) + "\n"
            self._reply(200, body, "application/json")
        elif path == "/tenants":
            # round 15: the tenant attribution + placement payload
            # (Session.serve_obs binds a getter so attribution enabled
            # AFTER the server started is still served — the /slo
            # provider discipline)
            payload = (obs.tenants() if callable(obs.tenants)
                       else obs.tenants)
            if payload is None:
                payload = {"enabled": False, "tenants": {}}
            body = json.dumps(payload, sort_keys=True) + "\n"
            self._reply(200, body, "application/json")
        elif path == "/journal":
            # round 22: the decision journal (getter-bound so a
            # recorder enabled AFTER the server started is served —
            # the /slo provider discipline)
            rec = (obs.recorder() if callable(obs.recorder)
                   else obs.recorder)
            payload = ({"enabled": False, "events": [], "counts": {}}
                       if rec is None else rec.journal.payload())
            body = json.dumps(payload, sort_keys=True,
                              default=repr) + "\n"
            self._reply(200, body, "application/json")
        elif path == "/incidents":
            rec = (obs.recorder() if callable(obs.recorder)
                   else obs.recorder)
            payload = ({"enabled": False, "incidents": []}
                       if rec is None else rec.incidents.payload())
            body = json.dumps(payload, sort_keys=True,
                              default=repr) + "\n"
            self._reply(200, body, "application/json")
        elif path == "/history":
            # round 23: the time-series store (getter-bound — same
            # late-enable discipline); ``?series=a,b`` filters.
            # Prometheus (/metrics) stays instantaneous — history is
            # JSON-only by design
            store = (obs.history() if callable(obs.history)
                     else obs.history)
            if store is None:
                payload = {"enabled": False, "series": {}}
            else:
                qs = parse_qs(urlsplit(self.path).query)
                names = qs.get("series")
                if names:
                    names = [n for arg in names
                             for n in arg.split(",") if n]
                payload = store.payload(series=names or None)
            body = json.dumps(payload, sort_keys=True) + "\n"
            self._reply(200, body, "application/json")
        elif path == "/forecast":
            fc = (obs.forecast() if callable(obs.forecast)
                  else obs.forecast)
            if fc is None:
                payload = {"enabled": False, "series": {}}
            else:
                qs = parse_qs(urlsplit(self.path).query)
                try:
                    horizon = float(qs.get("horizon_s", ["300"])[0])
                except ValueError:
                    horizon = 300.0
                payload = fc.payload(horizon_s=horizon)
            body = json.dumps(payload, sort_keys=True) + "\n"
            self._reply(200, body, "application/json")
        else:
            self._reply(404, "not found\n", "text/plain")

    def _reply(self, code: int, body: str, ctype: str):
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *args):  # quiet: scrapes are high-frequency
        pass


class ObsServer:
    """Opt-in observability endpoint over one Metrics (+Tracer).

    Binds 127.0.0.1 by an ephemeral port by default; ``url()`` gives
    the scrape target. Serving runs on a daemon thread; ``close()``
    shuts it down (also a context manager)."""

    def __init__(self, metrics, tracer=None, host: str = "127.0.0.1",
                 port: int = 0, ledger=None, slo=None, tenants=None,
                 attribution=None, numerics=None, quotas=None,
                 recorder=None, history=None, forecast=None):
        self.metrics = metrics
        self.tracer = tracer
        # the /slo provider: an SloTracker, or a zero-arg callable
        # resolved per request (Session.serve_obs passes a getter so a
        # tracker enabled AFTER the server started is still served)
        self.slo = slo
        # round 15: the /tenants payload provider and the attribution
        # ledger (or getters — same late-enable discipline as /slo);
        # attribution feeds the tenant_* sections of /metrics
        self.tenants = tenants
        self.attribution = attribution
        # round 16: the /numerics payload provider (or getter — same
        # late-enable discipline as /slo and /tenants)
        self.numerics = numerics
        # round 18: the quotas-payload provider for the /metrics
        # tenant-labeled quota rows (or getter — same discipline)
        self.quotas = quotas
        # round 22: the Recorder behind /journal + /incidents (or
        # getter — same late-enable discipline)
        self.recorder = recorder
        # round 23: the TimeseriesStore behind /history and the
        # Forecaster behind /forecast (or getters — same discipline)
        self.history = history
        self.forecast = forecast
        self.ledger = ledger if ledger is not None else flops_mod.LEDGER
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.obs = self  # type: ignore[attr-defined]
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="slate-tpu-obs-http", daemon=True)
        self._thread.start()

    def url(self, path: str = "/metrics") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
