from .types import (Uplo, Op, Diag, Side, Norm, NormScope, Direction, Layout,
                    GridOrder, MatrixKind, MethodGemm, MethodTrsm, MethodHemm,
                    MethodLU, MethodGels, MethodEig, MethodSVD, Options,
                    DEFAULT_OPTIONS)
from .exceptions import SlateError, slate_error_if, slate_assert
from .grid import (ProcessGrid, num_tiles, tile_dim, tile_rank_2d,
                   cyclic_permutation, inverse_permutation, gridinfo,
                   ROW_AXIS, COL_AXIS)
from .tiled_matrix import (TiledMatrix, from_dense, zeros, empty_like,
                           triangular, symmetric, hermitian, band,
                           hermitian_band, triangular_band, pad_mask,
                           pad_diag_identity)
