"""Matmul-precision policy for numerically sensitive drivers.

On TPU, jax's *default* matmul precision runs f32 contractions as fast
bfloat16-pass products (~2⁻¹⁴/pass effective mantissa, 3 passes). That is
the right trade for the gemm/symm BLAS-3 drivers (users control their own
precision there), but it destroys the backward stability budget of
factorizations — e.g. blocked-Householder Q orthogonality degrades from
1e-5 to 0.19 at n=512/f32 (measured on v5e). The reference never faces
this choice because cuBLAS runs true FP64.

``accurate_matmuls`` pins jax.default_matmul_precision("highest") (full
f32 accumulate on TPU; no-op on CPU f64) around a driver body. Applied to
every factorization/reflector path: potrf, getrf, geqrf/unmqr, he2hb,
ge2tb, heev, svd, hetrf.
"""

from __future__ import annotations

import functools

import jax


def accurate_matmuls(fn):
    """Decorator: run fn under full-precision matmuls."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with jax.default_matmul_precision("highest"):
            return fn(*args, **kwargs)

    return wrapped
