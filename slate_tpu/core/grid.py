"""Process grids and tile-distribution index maps.

TPU-native re-design of the reference's distribution layer:

- ``include/slate/func.hh`` (uniform_blocksize, process_2d_grid,
  device_2d_grid, 1D variants) becomes pure-Python/NumPy index functions
  here — they are *metadata*, evaluated at trace time.
- The MPI communicator + BLACS-style p×q rank grid
  (BaseMatrix.hh:778-780,792) becomes a ``jax.sharding.Mesh`` with named
  axes ``("p", "q")`` over real or virtual devices. XLA GSPMD plays the
  role of the MOSI coherency + tile broadcast machinery: annotating an
  array with a NamedSharding over this mesh is the analog of choosing a
  tileRank lambda.

2D block-cyclic ownership (the ScaLAPACK model, SURVEY §2.3 P1): global
tile (i, j) belongs to process (i mod p, j mod q). GSPMD shards arrays in
*contiguous* blocks, so we realize block-cyclic by a storage permutation:
tiles are packed so that each process's cyclic tile set is contiguous in
storage (see cyclic_permutation below). Drivers may use either the plain
contiguous layout (good for gemm-like ops, XLA picks SUMMA collectives)
or the cyclic packing (good for factorizations, balances the shrinking
trailing submatrix).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .types import GridOrder

ROW_AXIS = "p"
COL_AXIS = "q"


def num_tiles(n: int, nb: int) -> int:
    """ceil(n / nb) — number of tiles covering dimension n.

    Reference: slate::func::uniform_blocksize (include/slate/func.hh:39)
    paired with BaseMatrix::mt()/nt().
    """
    return -(-n // nb)


def tile_dim(i: int, n: int, nb: int) -> int:
    """Logical size of tile i (last tile may be ragged).

    On TPU storage is always padded to a full nb (SURVEY §7 risk (v):
    ragged last tiles are padded + masked rather than supported as
    non-uniform shapes), so this is only used for masking and flop math.
    """
    nt = num_tiles(n, nb)
    if i < 0 or i >= nt:
        return 0
    return n - i * nb if i == nt - 1 else nb


def tile_rank_2d(i: int, j: int, p: int, q: int, order: GridOrder = GridOrder.Col) -> int:
    """2D block-cyclic owner rank of tile (i, j).

    Reference: func::process_2d_grid (include/slate/func.hh:100-120).
    """
    if order is GridOrder.Col:
        return (i % p) + (j % q) * p
    return (i % p) * q + (j % q)


def local_tile_count(nt: int, p: int, pi: int) -> int:
    """How many of nt cyclic tiles land on grid coordinate pi of p."""
    return (nt - pi + p - 1) // p


def cyclic_permutation(nt: int, p: int) -> np.ndarray:
    """Permutation packing cyclic ownership into contiguous storage.

    Returns perm with perm[storage_index] = logical_tile_index such that
    storage slots [pi * ceil(nt/p), ...) hold exactly the tiles
    {i : i mod p == pi} in increasing order. Padded slots (when p does not
    divide nt) are appended per-process and map to -1.

    This is how the reference's tileRank block-cyclic lambda
    (BaseMatrix.hh:211-226) becomes a GSPMD-contiguous sharding.
    """
    per = -(-nt // p)  # ceil — every process gets the same padded count
    perm = np.full(p * per, -1, dtype=np.int64)
    for pi in range(p):
        mine = np.arange(pi, nt, p, dtype=np.int64)
        perm[pi * per : pi * per + mine.size] = mine
    return perm


def inverse_permutation(perm: np.ndarray) -> np.ndarray:
    inv = np.full(perm.size, -1, dtype=np.int64)
    valid = perm >= 0
    inv[perm[valid]] = np.nonzero(valid)[0]
    return inv


def tile_perm_row_indices(perm: np.ndarray, nb: int) -> np.ndarray:
    """Expand a tile permutation into element row indices: output row
    t·nb + r reads input row perm[t]·nb + r. Shared by the cyclic
    pack (shard(cyclic=True)) and unpack (_storage_logical) paths."""
    return (np.asarray(perm)[:, None] * nb
            + np.arange(nb, dtype=np.int64)[None, :]).ravel()


@dataclasses.dataclass(frozen=True)
class ProcessGrid:
    """A p×q grid of devices = jax Mesh with axes ("p", "q").

    Replaces the reference's (MPI_Comm, nprow, npcol, order) tuple
    (BaseMatrix.hh:778-792). ``mesh`` may span one real TPU chip (p=q=1),
    a slice's ICI torus, or a virtual CPU mesh in tests.
    """

    mesh: Mesh
    order: GridOrder = GridOrder.Col

    @property
    def p(self) -> int:
        return self.mesh.shape[ROW_AXIS]

    @property
    def q(self) -> int:
        return self.mesh.shape[COL_AXIS]

    @property
    def size(self) -> int:
        return self.p * self.q

    @staticmethod
    def create(
        p: Optional[int] = None,
        q: Optional[int] = None,
        devices: Optional[Sequence[jax.Device]] = None,
        order: GridOrder = GridOrder.Col,
    ) -> "ProcessGrid":
        """Build a p×q grid. With no arguments: near-square grid over all
        local devices (the analog of BLACS's default grid)."""
        if devices is None:
            devices = jax.devices()
        ndev = len(devices)
        if p is None and q is None:
            p = _near_square_factor(ndev)
            q = ndev // p
        elif p is None:
            p = ndev // q
        elif q is None:
            q = ndev // p
        if p * q > ndev:
            raise ValueError(f"grid {p}x{q} needs {p*q} devices, have {ndev}")
        dev_array = np.asarray(devices[: p * q]).reshape(p, q)
        return ProcessGrid(Mesh(dev_array, (ROW_AXIS, COL_AXIS)), order)

    @staticmethod
    def single() -> "ProcessGrid":
        return ProcessGrid.create(1, 1)

    def sharding(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def spec_2d(self) -> P:
        """Shard rows over p, cols over q — the default matrix layout."""
        return P(ROW_AXIS, COL_AXIS)

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())


def _near_square_factor(n: int) -> int:
    p = int(math.isqrt(n))
    while p > 1 and n % p != 0:
        p -= 1
    return p


def as_grid(mesh) -> Optional[ProcessGrid]:
    """Coerce a mesh-ish argument to a ProcessGrid (or None).

    Accepts ``None``, a :class:`ProcessGrid`, or a raw
    ``jax.sharding.Mesh`` whose axes are named ("p", "q") — the serving
    runtime's ``Session(mesh=...)`` entry point takes either spelling.
    A 1×1 grid coerces to ``None`` (single-device serving needs no
    distribution machinery)."""
    if mesh is None:
        return None
    if isinstance(mesh, ProcessGrid):
        grid = mesh
    elif isinstance(mesh, Mesh):
        if ROW_AXIS not in mesh.shape or COL_AXIS not in mesh.shape:
            raise ValueError(
                f"as_grid: mesh axes must be named ({ROW_AXIS!r}, "
                f"{COL_AXIS!r}), got {tuple(mesh.shape)}")
        grid = ProcessGrid(mesh)
    else:
        raise TypeError(
            f"as_grid: expected ProcessGrid, Mesh, or None — got "
            f"{type(mesh).__name__}")
    return grid if grid.size > 1 else None


def gridinfo(grid: ProcessGrid):
    """Reference: BaseMatrix::gridinfo (BaseMatrix.hh:161) — reverse lookup
    of (order, p, q). Trivial here because the grid is first-class."""
    return grid.order, grid.p, grid.q
