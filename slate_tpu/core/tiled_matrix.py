"""TiledMatrix — the distributed tiled matrix data model.

TPU-native re-design of the reference's L1/L2 storage stack:

- ``BaseMatrix`` (include/slate/BaseMatrix.hh:40, 3,976 lines of view state,
  MOSI coherency, MPI broadcast/reduce) collapses to a small immutable
  pytree: a padded dense ``jax.Array`` plus tile/view metadata. There is no
  MOSI protocol and no receive_count life-cycle — a sharded ``jax.Array``
  over a Mesh *is* the single-source-of-truth distributed matrix, and XLA
  GSPMD inserts the equivalents of tileBcast/listBcast/listReduce
  (BaseMatrix.hh:1958-2245) as all-gather/reduce-scatter/collective-permute
  over ICI when drivers request reshardings.
- ``MatrixStorage``/``TileNode``/``Memory`` (include/slate/internal/
  MatrixStorage.hh, Memory.hh) have no analog: XLA owns device memory.
- ``Tile`` (include/slate/Tile.hh:106) becomes a logical (nb, nb) slice of
  the padded storage — see tile()/with_tile().
- Matrix kinds (Matrix.hh + 10 subclasses, include/slate/*.hh) become a
  ``MatrixKind`` metadata field plus constructor helpers; band kinds carry
  (kl, ku). Round 1 stores band matrices as masked dense; packed band
  storage is a later optimization.

Semantics difference, by design: the reference's sub()/slice() return
*views that alias and mutate* the parent. JAX is functional — our sub/slice
return independent values, and drivers return new matrices instead of
mutating in place. transpose()/conj_transpose() remain zero-copy metadata
flips exactly like the reference (BaseMatrix.hh:140-148).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .exceptions import SlateError
from .grid import ProcessGrid, num_tiles, tile_dim
from .types import Diag, MatrixKind, Op, Uplo


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class TiledMatrix:
    """An (m × n) matrix stored as padded (mt·nb × nt·nb) dense data.

    ``data`` is always in NoTrans orientation; ``op`` is a view flag applied
    lazily by dense()/tile(). Padding rows/cols beyond (m, n) are zero.
    """

    data: jax.Array
    m: int
    n: int
    nb: int
    kind: MatrixKind = MatrixKind.General
    uplo: Uplo = Uplo.General
    op: Op = Op.NoTrans
    diag: Diag = Diag.NonUnit
    kl: int = 0
    ku: int = 0
    grid: Optional[ProcessGrid] = None
    # storage is 2D BLOCK-CYCLIC over the grid: storage tile-row s holds
    # logical tile-row cyclic_permutation(mt, p)[s] (ditto columns over
    # q). The ScaLAPACK-model layout (reference func::process_2d_grid,
    # include/slate/func.hh:100-120): contiguous GSPMD shards of the
    # permuted storage are exactly the cyclic tile sets, so each device
    # owns tiles {i : i mod p == pi}. dense() unpermutes to logical
    # order (one gather = collective-permute over ICI).
    cyclic: bool = False
    # factor-packing tag ("aasen", "ldl", ...): lets solvers reject a
    # factor produced under a DIFFERENT packing than they consume
    # (hetrf-RBT vs hetrs, ADVICE r4) instead of silently computing a
    # wrong X. Empty = not a tagged factor.
    packing: str = ""

    # -- pytree ----------------------------------------------------------
    def tree_flatten(self):
        meta = (self.m, self.n, self.nb, self.kind, self.uplo, self.op,
                self.diag, self.kl, self.ku, self.grid, self.cyclic,
                self.packing)
        return (self.data,), meta

    @classmethod
    def tree_unflatten(cls, meta, children):
        (data,) = children
        (m, n, nb, kind, uplo, op, diag, kl, ku, grid, cyclic,
         packing) = meta
        return cls(data, m, n, nb, kind, uplo, op, diag, kl, ku, grid,
                   cyclic, packing)

    # -- shape / tiles (op-adjusted, like BaseMatrix::m()/n()/mt()/nt()) --
    @property
    def shape(self):
        return (self.m, self.n) if self.op is Op.NoTrans else (self.n, self.m)

    @property
    def mt(self) -> int:
        """Tile-rows of the *view* (reference BaseMatrix::mt())."""
        return num_tiles(self.shape[0], self.nb)

    @property
    def nt(self) -> int:
        return num_tiles(self.shape[1], self.nb)

    @property
    def dtype(self):
        return self.data.dtype

    def tile_mb(self, i: int) -> int:
        return tile_dim(i, self.shape[0], self.nb)

    def tile_nb(self, j: int) -> int:
        return tile_dim(j, self.shape[1], self.nb)

    # -- views (zero-copy metadata flips) --------------------------------
    def transpose(self) -> "TiledMatrix":
        """Reference: slate::transpose (BaseMatrix.hh:140-148)."""
        new_op = {Op.NoTrans: Op.Trans, Op.Trans: Op.NoTrans,
                  Op.ConjTrans: Op.NoTrans}[self.op]
        conj_leftover = self.op is Op.ConjTrans  # (Aᴴ)ᵀ = conj(A)
        if conj_leftover:
            return dataclasses.replace(self, data=jnp.conj(self.data),
                                       op=new_op, uplo=self.uplo.flipped(),
                                       kl=self.ku, ku=self.kl)
        return dataclasses.replace(self, op=new_op, uplo=self.uplo.flipped(),
                                   kl=self.ku, ku=self.kl)

    def conj_transpose(self) -> "TiledMatrix":
        new_op = {Op.NoTrans: Op.ConjTrans, Op.ConjTrans: Op.NoTrans,
                  Op.Trans: Op.NoTrans}[self.op]
        if self.op is Op.Trans:  # (Aᵀ)ᴴ = conj(A)
            return dataclasses.replace(self, data=jnp.conj(self.data),
                                       op=new_op, uplo=self.uplo.flipped(),
                                       kl=self.ku, ku=self.kl)
        return dataclasses.replace(self, op=new_op, uplo=self.uplo.flipped(),
                                   kl=self.ku, ku=self.kl)

    @property
    def T(self) -> "TiledMatrix":
        return self.transpose()

    @property
    def H(self) -> "TiledMatrix":
        return self.conj_transpose()

    # -- materialization -------------------------------------------------
    def _storage_logical(self) -> jax.Array:
        """Storage in logical (NoTrans) tile order — unpermutes cyclic
        packing when present."""
        if not self.cyclic:
            return self.data
        from .grid import (cyclic_permutation, inverse_permutation,
                           tile_perm_row_indices)
        p = self.grid.p if self.grid is not None else 1
        q = self.grid.q if self.grid is not None else 1
        nb = self.nb
        mtp = self.data.shape[0] // nb
        ntp = self.data.shape[1] // nb
        ridx = tile_perm_row_indices(
            inverse_permutation(cyclic_permutation(mtp, p)), nb)
        cidx = tile_perm_row_indices(
            inverse_permutation(cyclic_permutation(ntp, q)), nb)
        return self.data[jnp.asarray(ridx)][:, jnp.asarray(cidx)]

    def dense(self) -> jax.Array:
        """Padded dense array with op applied (shape mt·nb × nt·nb of the
        view). The workhorse used by drivers; XLA fuses the transpose."""
        base = self._storage_logical()
        if self.op is Op.NoTrans:
            return base
        if self.op is Op.Trans:
            return base.T
        return jnp.conj(base).T

    def dense_canonical(self) -> jax.Array:
        """Padded dense of the view at the *canonical* size (mt·nb, nt·nb),
        cropping or zero-padding any extra grid-rounding padding (see
        shard()). Drivers use this so operand shapes always line up."""
        a = self.dense()
        rows, cols = self.mt * self.nb, self.nt * self.nb
        if a.shape == (rows, cols):
            return a
        a = a[:rows, :cols]
        if a.shape != (rows, cols):
            a = jnp.pad(a, ((0, rows - a.shape[0]), (0, cols - a.shape[1])))
        return a

    def full_dense_canonical(self) -> jax.Array:
        """full_dense() cropped/padded to the canonical (mt·nb, nt·nb)
        size — the form drivers must use so operand shapes line up
        regardless of grid-rounding padding (see shard())."""
        a = self.full_dense()
        rows, cols = self.mt * self.nb, self.nt * self.nb
        if a.shape == (rows, cols):
            return a
        a = a[:rows, :cols]
        if a.shape != (rows, cols):
            a = jnp.pad(a, ((0, rows - a.shape[0]), (0, cols - a.shape[1])))
        return a

    def to_numpy(self) -> np.ndarray:
        """Crop padding and return the logical (view-shaped) matrix."""
        mm, nn = self.shape
        return np.asarray(self.dense()[:mm, :nn])

    def to_dense(self) -> jax.Array:
        mm, nn = self.shape
        return self.dense()[:mm, :nn]

    def full_dense(self) -> jax.Array:
        """Materialize implicit structure: mirror the stored triangle for
        Symmetric/Hermitian kinds, apply unit diagonal / zero the strict
        opposite triangle for Triangular, band-mask Band kinds. Used by
        checks, norms, and drivers that need an explicit operand.

        Operates at the CANONICAL (mt·nb, nt·nb) size: grid-rounding
        padding can make raw storage non-square, and mirroring a
        non-square array would be ill-formed."""
        a = self.dense_canonical()
        npad = a.shape
        if self.kind in (MatrixKind.Symmetric, MatrixKind.Hermitian):
            tri_l = jnp.tril(a)
            tri_u = jnp.triu(a)
            if self.kind is MatrixKind.Hermitian:
                if self.uplo is Uplo.Lower:
                    a = tri_l + jnp.conj(jnp.tril(a, -1)).T
                else:
                    a = tri_u + jnp.conj(jnp.triu(a, 1)).T
                # force real diagonal for Hermitian
                if jnp.iscomplexobj(a):
                    d = jnp.real(jnp.diagonal(a))
                    a = a - jnp.diag(jnp.diagonal(a)) + jnp.diag(d).astype(a.dtype)
            else:
                if self.uplo is Uplo.Lower:
                    a = tri_l + jnp.tril(a, -1).T
                else:
                    a = tri_u + jnp.triu(a, 1).T
        elif self.kind in (MatrixKind.Triangular, MatrixKind.Trapezoid,
                           MatrixKind.TriangularBand):
            a = jnp.tril(a) if self.uplo is Uplo.Lower else jnp.triu(a)
            if self.diag is Diag.Unit:
                eye = jnp.eye(npad[0], npad[1], dtype=a.dtype)
                a = a - jnp.diag(jnp.diagonal(a)) + eye
        if self.kind in (MatrixKind.Band, MatrixKind.TriangularBand,
                         MatrixKind.HermitianBand):
            kl = self.kl if self.uplo in (Uplo.General, Uplo.Lower) else 0
            ku = self.ku if self.uplo in (Uplo.General, Uplo.Upper) else 0
            if self.kind is MatrixKind.HermitianBand:
                kl = ku = self.kl or self.ku
            r = jnp.arange(npad[0])[:, None]
            c = jnp.arange(npad[1])[None, :]
            mask = (c - r <= ku) & (r - c <= kl)
            a = jnp.where(mask, a, jnp.zeros((), a.dtype))
            if self.kind is MatrixKind.HermitianBand:
                a = jnp.tril(a) + jnp.conj(jnp.tril(a, -1)).T if self.uplo is Uplo.Lower \
                    else jnp.triu(a) + jnp.conj(jnp.triu(a, 1)).T
        return a

    # -- tiles -----------------------------------------------------------
    def tile(self, i: int, j: int) -> jax.Array:
        """The (nb, nb) padded tile at tile-index (i, j) of the view.

        Reference: BaseMatrix::operator()(i, j) returning a Tile
        (include/slate/Tile.hh:106). Static slice when i, j are Python ints.
        """
        a = self.dense()
        nb = self.nb
        return jax.lax.slice(a, (i * nb, j * nb), ((i + 1) * nb, (j + 1) * nb))

    def with_tile(self, i: int, j: int, val: jax.Array) -> "TiledMatrix":
        if self.op is not Op.NoTrans:
            raise SlateError("with_tile requires a NoTrans view")
        if self.cyclic:
            raise SlateError("with_tile requires contiguous (non-cyclic) "
                             "storage; use shard(grid) first")
        data = jax.lax.dynamic_update_slice(self.data, val.astype(self.dtype),
                                            (i * self.nb, j * self.nb))
        return dataclasses.replace(self, data=data)

    def with_data(self, data: jax.Array) -> "TiledMatrix":
        return dataclasses.replace(self, data=data)

    # -- sub-matrix ------------------------------------------------------
    def sub(self, i1: int, i2: int, j1: int, j2: int) -> "TiledMatrix":
        """Tile-index sub-matrix, inclusive ranges like the reference
        (BaseMatrix::sub, BaseMatrix.hh:sub). Returns an independent value
        (functional semantics), kind demoted to General/Trapezoid rules
        are the caller's business."""
        nb = self.nb
        a = self.dense()
        i2 = min(i2, self.mt - 1)
        j2 = min(j2, self.nt - 1)
        if i2 < i1 or j2 < j1:
            rows = max(0, i2 - i1 + 1) * nb
            cols = max(0, j2 - j1 + 1) * nb
            return TiledMatrix(jnp.zeros((rows, cols), self.dtype), 0, 0, nb,
                               grid=self.grid)
        block = a[i1 * nb:(i2 + 1) * nb, j1 * nb:(j2 + 1) * nb]
        mm, nn = self.shape
        sub_m = min(mm, (i2 + 1) * nb) - i1 * nb
        sub_n = min(nn, (j2 + 1) * nb) - j1 * nb
        return TiledMatrix(block, sub_m, sub_n, nb, kind=MatrixKind.General,
                           grid=self.grid)

    def slice(self, row1: int, row2: int, col1: int, col2: int) -> "TiledMatrix":
        """Element-index slice (inclusive), re-tiled from offset 0.

        Reference: BaseMatrix::slice (BaseMatrix.hh:770-773 offsets). We
        re-pack instead of keeping offsets — one XLA slice+pad."""
        sub_m = row2 - row1 + 1
        sub_n = col2 - col1 + 1
        a = self.to_dense()[row1:row2 + 1, col1:col2 + 1]
        return from_dense(a, self.nb, grid=self.grid, logical_shape=(sub_m, sub_n))

    # -- sharding --------------------------------------------------------
    def shard(self, grid: ProcessGrid, spec: Optional[P] = None,
              cyclic: bool = False) -> "TiledMatrix":
        """Place storage on the grid with rows over 'p', cols over 'q'.

        The analog of constructing a matrix with process_2d_grid tileRank
        lambdas (func.hh:100-120). GSPMD requires even shards, so storage
        is padded up to tile counts divisible by (p, q) — the moral
        equivalent of ScaLAPACK's padded local arrays.

        cyclic=True packs tiles 2D block-cyclically before sharding
        (see the ``cyclic`` field): device (pi, qi) then owns exactly
        the ScaLAPACK tile set {(i, j) : i mod p = pi, j mod q = qi}."""
        from .grid import cyclic_permutation, tile_perm_row_indices
        spec = spec if spec is not None else grid.spec_2d()
        nb = self.nb
        data = self._storage_logical()
        rows = -(-data.shape[0] // (grid.p * nb)) * grid.p * nb
        cols = -(-data.shape[1] // (grid.q * nb)) * grid.q * nb
        if (rows, cols) != data.shape:
            data = jnp.pad(data, ((0, rows - data.shape[0]),
                                  (0, cols - data.shape[1])))
        if cyclic:
            ridx = tile_perm_row_indices(
                cyclic_permutation(rows // nb, grid.p), nb)
            cidx = tile_perm_row_indices(
                cyclic_permutation(cols // nb, grid.q), nb)
            data = data[jnp.asarray(ridx)][:, jnp.asarray(cidx)]
        data = jax.device_put(data, NamedSharding(grid.mesh, spec))
        return dataclasses.replace(self, data=data, grid=grid,
                                   cyclic=cyclic)

    def constrain(self, spec: P) -> "TiledMatrix":
        """with_sharding_constraint under jit (needs self.grid)."""
        if self.grid is None:
            return self
        data = jax.lax.with_sharding_constraint(
            self.data, NamedSharding(self.grid.mesh, spec))
        return dataclasses.replace(self, data=data)


# ---------------------------------------------------------------------------
# Constructors (analog of Matrix::fromLAPACK / emptyLike / insertLocalTiles,
# include/slate/Matrix.hh:58-164, and the kind subclasses)
# ---------------------------------------------------------------------------


def _pad_to_tiles(a: jax.Array, nb: int) -> jax.Array:
    m, n = a.shape
    mp = num_tiles(m, nb) * nb
    np_ = num_tiles(n, nb) * nb
    if mp == m and np_ == n:
        return a
    return jnp.pad(a, ((0, mp - m), (0, np_ - n)))


def from_dense(a, nb: int, grid: Optional[ProcessGrid] = None,
               kind: MatrixKind = MatrixKind.General,
               uplo: Uplo = Uplo.General, diag: Diag = Diag.NonUnit,
               kl: int = 0, ku: int = 0,
               logical_shape=None) -> TiledMatrix:
    """Build a TiledMatrix from a dense array (host or device).

    The analog of Matrix::fromLAPACK (include/slate/Matrix.hh:58): wraps
    user data in the tiled/distributed structure. Data is padded to whole
    tiles with zeros.
    """
    a = jnp.asarray(a)
    if a.ndim != 2:
        raise SlateError("from_dense expects a 2-D array")
    m, n = logical_shape if logical_shape is not None else a.shape
    a = _pad_to_tiles(a, nb)
    if logical_shape is not None and (m < a.shape[0] or n < a.shape[1]):
        # invariant: storage beyond the logical shape is zero (drivers
        # rely on it — e.g. trsm's unit-padded diagonal, solves with
        # zero-padded rhs)
        r = jnp.arange(a.shape[0])[:, None] < m
        c = jnp.arange(a.shape[1])[None, :] < n
        a = jnp.where(r & c, a, jnp.zeros((), a.dtype))
    t = TiledMatrix(a, m, n, nb, kind=kind, uplo=uplo, diag=diag, kl=kl, ku=ku,
                    grid=grid)
    if grid is not None:
        t = t.shard(grid)
    return t


def zeros(m: int, n: int, nb: int, dtype=jnp.float32,
          grid: Optional[ProcessGrid] = None, **kw) -> TiledMatrix:
    mp = num_tiles(m, nb) * nb
    np_ = num_tiles(n, nb) * nb
    t = TiledMatrix(jnp.zeros((mp, np_), dtype), m, n, nb, grid=grid, **kw)
    if grid is not None:
        t = t.shard(grid)
    return t


def empty_like(a: TiledMatrix, m: Optional[int] = None, n: Optional[int] = None,
               dtype=None) -> TiledMatrix:
    """Reference: BaseMatrix::emptyLike (Matrix.hh:117)."""
    mm = m if m is not None else a.shape[0]
    nn = n if n is not None else a.shape[1]
    return zeros(mm, nn, a.nb, dtype or a.dtype, grid=a.grid)


def triangular(a, nb: int, uplo: Uplo, diag: Diag = Diag.NonUnit,
               grid=None) -> TiledMatrix:
    """TriangularMatrix analog (include/slate/TriangularMatrix.hh)."""
    return from_dense(a, nb, grid=grid, kind=MatrixKind.Triangular, uplo=uplo,
                      diag=diag)


def symmetric(a, nb: int, uplo: Uplo, grid=None) -> TiledMatrix:
    return from_dense(a, nb, grid=grid, kind=MatrixKind.Symmetric, uplo=uplo)


def hermitian(a, nb: int, uplo: Uplo, grid=None) -> TiledMatrix:
    return from_dense(a, nb, grid=grid, kind=MatrixKind.Hermitian, uplo=uplo)


def band(a, nb: int, kl: int, ku: int, grid=None) -> TiledMatrix:
    """BandMatrix analog (include/slate/BandMatrix.hh). Round 1: masked
    dense storage."""
    return from_dense(a, nb, grid=grid, kind=MatrixKind.Band, kl=kl, ku=ku)


def hermitian_band(a, nb: int, kd: int, uplo: Uplo, grid=None) -> TiledMatrix:
    return from_dense(a, nb, grid=grid, kind=MatrixKind.HermitianBand,
                      uplo=uplo, kl=kd, ku=kd)


def triangular_band(a, nb: int, kd: int, uplo: Uplo, diag: Diag = Diag.NonUnit,
                    grid=None) -> TiledMatrix:
    kl, ku = (kd, 0) if uplo is Uplo.Lower else (0, kd)
    return from_dense(a, nb, grid=grid, kind=MatrixKind.TriangularBand,
                      uplo=uplo, diag=diag, kl=kl, ku=ku)


def pad_mask(t: TiledMatrix) -> jax.Array:
    """Boolean mask of logical (non-padding) entries at the canonical
    padded size (matches full_dense())."""
    mm, nn = t.shape
    r = jnp.arange(t.mt * t.nb)[:, None] < mm
    c = jnp.arange(t.nt * t.nb)[None, :] < nn
    return r & c


def unit_pad_diag(a: jax.Array, m_log: int, n_log: int) -> jax.Array:
    """Set 1 on the diagonal of the padding region (rows/cols beyond the
    logical (m_log, n_log)). The single shared helper behind every
    factorization's 'padded system is block-diag [[A,0],[0,I]]' trick
    (SURVEY §7 risk (v))."""
    idx = jnp.arange(min(a.shape))
    d = jnp.diagonal(a)[: idx.size]
    on_pad = (idx >= m_log) | (idx >= n_log)
    return a.at[idx, idx].set(jnp.where(on_pad, jnp.ones((), a.dtype), d))


def pad_diag_identity(t: TiledMatrix) -> TiledMatrix:
    """Put 1 on the padded part of the diagonal so factorizations of the
    padded storage stay well-defined (SURVEY §7 risk (v)). The padding is
    cropped away by to_dense(), and zero rhs padding keeps solves exact."""
    if t.cyclic:
        raise SlateError("pad_diag_identity requires contiguous storage")
    return t.with_data(unit_pad_diag(t.data, t.m, t.n))
