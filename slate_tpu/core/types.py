"""Enums and option types for slate-tpu.

TPU-native re-design of the reference's enum/option vocabulary
(reference: include/slate/enums.hh, include/slate/types.hh). We keep the
same *semantic* vocabulary (Uplo/Op/Diag/Side/Norm, per-routine Method
enums, an Options bag) but express it as plain Python enums/dataclasses:
there is no Target::{HostTask,HostNest,HostBatch,Devices} dispatch here —
XLA owns scheduling, so the "target" axis collapses to how a routine is
jitted/sharded (see slate_tpu.parallel).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional


class Uplo(enum.Enum):
    """Which triangle of a matrix is stored/referenced.

    Reference: include/slate/enums.hh (blas::Uplo re-export).
    """

    General = "g"
    Lower = "l"
    Upper = "u"

    def flipped(self) -> "Uplo":
        if self is Uplo.Lower:
            return Uplo.Upper
        if self is Uplo.Upper:
            return Uplo.Lower
        return self


class Op(enum.Enum):
    """Transposition view state (zero-copy in the reference; metadata here).

    Reference: BaseMatrix::op_ (include/slate/BaseMatrix.hh:783-786) and the
    transpose/conj_transpose free functions (BaseMatrix.hh:140-148).
    """

    NoTrans = "n"
    Trans = "t"
    ConjTrans = "c"


class Diag(enum.Enum):
    NonUnit = "n"
    Unit = "u"


class Side(enum.Enum):
    Left = "l"
    Right = "r"


class Norm(enum.Enum):
    """Matrix norm kind. Reference: include/slate/enums.hh (lapack::Norm)."""

    One = "1"
    Two = "2"
    Inf = "i"
    Fro = "f"
    Max = "m"


class NormScope(enum.Enum):
    """Reference: enums.hh:514 (NormScope{Columns,Rows,Matrix})."""

    Matrix = "m"
    Columns = "c"
    Rows = "r"


class Direction(enum.Enum):
    Forward = "f"
    Backward = "b"


class Layout(enum.Enum):
    """Kept for API parity; on TPU all storage is row-major jax.Arrays and
    layout conversion (reference BaseMatrix.hh:551-603) is a no-op/XLA detail.
    """

    ColMajor = "c"
    RowMajor = "r"


class GridOrder(enum.Enum):
    """2D process-grid ordering. Reference: enums.hh:524 GridOrder."""

    Col = "c"
    Row = "r"


class MatrixKind(enum.Enum):
    """Which matrix-kind a TiledMatrix represents.

    The reference uses a subclass per kind (Matrix, TrapezoidMatrix,
    TriangularMatrix, SymmetricMatrix, HermitianMatrix, BandMatrix,
    TriangularBandMatrix, HermitianBandMatrix — one header each in
    include/slate/). Here kinds are a metadata field on one pytree class;
    thin constructor aliases live in slate_tpu.core.tiled_matrix.
    """

    General = "ge"
    Trapezoid = "tz"
    Triangular = "tr"
    Symmetric = "sy"
    Hermitian = "he"
    Band = "gb"
    TriangularBand = "tb"
    HermitianBand = "hb"


# ---------------------------------------------------------------------------
# Per-routine algorithm-variant enums ("Methods").
# Reference: include/slate/enums.hh:61-455 and §2.3/P10 of SURVEY.md.
# ---------------------------------------------------------------------------


class MethodGemm(enum.Enum):
    Auto = "auto"
    A = "A"  # stationary-A: partial products where A lives, then reduce
    C = "C"  # stationary-C: broadcast A column / B row panels (SUMMA)
    # explicit hand-scheduled SUMMA via shard_map + ring broadcasts
    # (parallel/summa.gemm_summa) instead of GSPMD constraint inference
    SUMMA = "summa"


class MethodTrsm(enum.Enum):
    Auto = "auto"
    A = "A"
    B = "B"


class MethodHemm(enum.Enum):
    Auto = "auto"
    A = "A"
    C = "C"


class MethodLU(enum.Enum):
    """Reference: enums.hh:302 MethodLU; dispatch in src/getrf.cc:324-353
    (PartialPiv/CALU/NoPiv wired; RBT via gesv_rbt entry point)."""

    Auto = "auto"
    PartialPiv = "ppiv"
    CALU = "calu"
    NoPiv = "nopiv"
    RBT = "rbt"


class MethodGels(enum.Enum):
    Auto = "auto"
    QR = "qr"
    CholQR = "cholqr"


class MethodHesv(enum.Enum):
    """Hermitian-indefinite factorization variant (the reference ships
    pivoted Aasen, src/hetrf.cc; RBT is our no-pivot LDLᴴ trade)."""

    Auto = "auto"      # = Aasen (pivoted — deterministic stability)
    Aasen = "aasen"    # LTLᴴ with symmetric partial pivoting
    RBT = "rbt"        # symmetric butterfly + no-pivot LDLᴴ + IR


class MethodEig(enum.Enum):
    Auto = "auto"
    QR = "qr"  # steqr QR iteration
    DC = "dc"  # divide & conquer


class MethodSVD(enum.Enum):
    Auto = "auto"
    QR = "qr"
    DC = "dc"


class TileReleaseStrategy(enum.Enum):
    """Kept for API parity only: workspace life-cycle is XLA's job on TPU."""

    None_ = "none"
    Internal = "internal"
    Slate = "slate"
    All = "all"


# ---------------------------------------------------------------------------
# Options
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Options:
    """Per-call options bag.

    Reference: slate::Options = std::map<Option, OptionValue>
    (include/slate/types.hh:24-80, keys at enums.hh:461-498). We use a typed
    frozen dataclass; every driver takes ``opts: Options = Options()`` as its
    last argument, mirroring the reference call convention.

    Fields that only make sense under MPI/OpenMP (MaxPanelThreads, Target,
    HoldLocalWorkspace, TileReleaseStrategy) are kept as inert parity fields.
    """

    # Depth of the factorization pipeline (the reference's
    # Option::Lookahead, enums.hh:461-498; functional since round 7).
    # ≥ 1: the iterative outer loops of potrf/getrf/geqrf split each
    # trailing update at the next-panel slab and factor panel k+1
    # between the slab and the remainder, so the serial panel chain of
    # step k+1 carries no data edge to step k's remainder gemms and the
    # scheduler may interleave them (lookahead-1 — PLASMA/DPLASMA
    # lineage puts most of the win there). 0 = the strictly sequential
    # round-6 schedule (bit-identical results; the reference arm for
    # tests and A/B timing). Depths > 1 CLAMP to 1 with a one-time
    # warning at the driver consumption seam (normalize_lookahead,
    # below): the pipeline implements depth 1, and round 21's
    # autotuner must not search a dimension that is a no-op — the
    # clamp (and its bit-identity to depth 1) is pinned in
    # tests/test_tuning.py.
    lookahead: int = 1
    block_size: int = 256  # nb — tile size
    inner_blocking: int = 32  # ib — panel inner blocking
    max_panel_threads: int = 1  # parity only
    tolerance: Optional[float] = None
    max_iterations: int = 30
    use_fallback_solver: bool = True
    pivot_threshold: float = 1.0
    depth: int = 2  # RBT butterfly depth
    # Matmul precision for the large trailing-update gemms of the
    # factorization drivers. On TPU "high" = bf16x3 passes (≈ f32-accurate,
    # 2× the "highest" rate, measured 60.7 vs 30.7 TFLOP/s on v5e); panel
    # and reflector math always runs at "highest" (core/precision.py).
    # No analog in the reference (cuBLAS runs native fp64); closest is
    # the gemm-autotuning Target/Method machinery.
    update_precision: str = "high"
    # Method selection (P10):
    method_gemm: MethodGemm = MethodGemm.Auto
    method_trsm: MethodTrsm = MethodTrsm.Auto
    method_hemm: MethodHemm = MethodHemm.Auto
    method_lu: MethodLU = MethodLU.Auto
    # explicit shard_map panel factorization for getrf: per-column
    # maxloc pivot collective + masked-psum row swaps over the grid row
    # axis (parallel/panel.py — the hand-scheduled counterpart of the
    # GSPMD-inferred panel; reference Tile_getrf.hh:209-270)
    lu_dist_panel: bool = False
    # Round-6 fast paths (PERF.md "Round 6"). lu_pivot_fusion: fold the
    # per-level row permutation into the trailing-update gemm READS
    # (gather-as-you-read + deferred left swaps) instead of
    # materializing a full-width permuted copy per level — the
    # TPU-native analog of the reference's device-batched swaps
    # (internal_swap.cc:503-560). False restores the materialized-copy
    # reference path (bit-identical results; kept for A/B + tests).
    lu_pivot_fusion: bool = True
    # Round 7: CALU tournament rounds as ONE batched panel LU per round
    # (blocked.panel_getrf_batched) instead of vmap(lax.linalg.lu)'s
    # sequential per-block custom-call loop. False restores the
    # lax.linalg.lu rounds (A/B timing + dispatch-policy reference;
    # winner selection may differ between arms — both valid tournament
    # pivotings).
    lu_tournament_batched: bool = True
    # factor_iter_large: run the right-looking iterative outer loop with
    # in-place (dynamic_update_slice) trailing updates at ALL sizes with
    # nt ≤ 64 for potrf/getrf — the round-5 n=2048 crossover was set by
    # the loop's concatenation/permute-copy traffic, which the in-place
    # slab updates and pivot fusion remove. False restores the 2×2
    # recursion dispatch above the old crossover.
    factor_iter_large: bool = True
    method_gels: MethodGels = MethodGels.Auto
    method_hesv: MethodHesv = MethodHesv.Auto
    method_eig: MethodEig = MethodEig.Auto
    # stage-1 reduction strategy for the DC eigensolver path:
    # "he2td" = direct blocked tridiagonalization (one stage, half the
    # flops in sequential full-matrix matvecs); "two_stage" = he2hb
    # band reduction (all-gemm) + hb2td bulge chase on O(n·nb) data
    # (the reference's he2hb+hb2st split, src/he2hb.cc + src/hb2st.cc);
    # "auto" picks per backend/size (see eig._heev_td and PERF.md)
    eig_stage1: str = "auto"
    method_svd: MethodSVD = MethodSVD.Auto
    # printing (reference enums.hh:477-487)
    print_verbose: int = 4
    print_edgeitems: int = 16
    print_width: int = 10
    print_precision: int = 4

    def replace(self, **kw) -> "Options":
        return dataclasses.replace(self, **kw)


DEFAULT_OPTIONS = Options()

# one-time-warning latch for normalize_lookahead (process-wide: the
# point is not to spam a serving log once per solve)
_LOOKAHEAD_WARNED = False


def normalize_lookahead(depth: int) -> int:
    """The effective pipeline depth for a requested ``lookahead``.

    The round-7 pipeline implements depths 0 and 1; deeper requests
    used to be silently scheduled as depth 1 (the old ``Options``
    comment admitted it). Round 21 makes that contract explicit —
    the autotuner must not search a dimension that is a no-op:
    negative depths are rejected, depths > 1 CLAMP to 1 with a
    one-time warning, and the clamped schedule is bit-identical to an
    explicit depth-1 run (pinned in tests/test_tuning.py). Called at
    the driver consumption seams (cholesky/lu/qr), so every entry
    point — Options, tuning tables, direct kwargs — shares one rule.
    """
    global _LOOKAHEAD_WARNED
    depth = int(depth)
    if depth < 0:
        raise ValueError(f"Options.lookahead must be >= 0, got {depth}")
    if depth > 1:
        if not _LOOKAHEAD_WARNED:
            _LOOKAHEAD_WARNED = True
            import warnings
            warnings.warn(
                f"Options.lookahead={depth} clamps to 1: the "
                "factorization pipeline implements lookahead-1 "
                "(PLASMA/DPLASMA lineage puts most of the win there); "
                "deeper depths schedule identically. This warning is "
                "emitted once per process.", stacklevel=2)
        return 1
    return depth
