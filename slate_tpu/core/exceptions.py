"""Error handling for slate-tpu.

Reference: include/slate/Exception.hh (slate::Exception, slate_error,
slate_assert, MPI/LAPACK error translation). On TPU there is no MPI error
class; numerical "info" codes from factorizations are returned as values
(jit-compatible), and host-side argument validation raises SlateError.
"""

from __future__ import annotations


class SlateError(RuntimeError):
    """Analog of slate::Exception (include/slate/Exception.hh:1-126)."""


def slate_error_if(cond: bool, msg: str) -> None:
    if cond:
        raise SlateError(msg)


def slate_assert(cond: bool, msg: str = "assertion failed") -> None:
    if not cond:
        raise SlateError(msg)
