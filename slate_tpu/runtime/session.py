"""Resident-factorization solve service.

A ``Session`` owns device-resident factored operators (LU / Cholesky /
QR / banded) keyed by a user handle, so N solve requests against the
same operator pay ONE factorization — the TPU-native generalization of
the reference tester's persistent-matrix + ``*_solve_using_factor``
amortization (include/slate/simplified_api.hh), grown into a serving
component: an HBM-byte-budget LRU cache over the factors, explicit
eviction, refactor-on-miss, AOT compile warmup, and serving metrics.

Layering: the Session only calls the public simplified-API verbs
(``lu_factor``/``lu_solve_using_factor``, ``chol_factor``/..., the new
``qr_factor``/``least_squares_solve_using_factor``), so anything those
verbs learn (method dispatch, precision policy, sharding) is served
automatically. The C API's opaque-handle solves (compat/c_glue.py)
route through a process-wide ``default_session()`` so native callers
share the same cache.

**Mesh-native serving (round 11).** ``Session(mesh=...)`` (or
``register(A, mesh=...)``) makes the service pod-scale: a dense
operator registered against a p×q :class:`~..core.grid.ProcessGrid` is
2D-block placed over the mesh at registration (``TiledMatrix.shard`` —
the ``NamedSharding`` analog of the reference's ``BaseMatrix``
2D-block-cyclic layout), its factor is computed by the existing mesh
drivers (the GSPMD-partitioned blocked loops plus the explicit
``parallel/`` schedules the Options select) and stays **resident as a
sharded array across the mesh** — so aggregate HBM, not one chip's, is
the capacity ceiling. Mesh solves always run as ONE AOT-compiled
sharded program per (op, operand shapes, dtype, mesh): the first touch
of a shape compiles at the `_aot_compile` seam (off the request path
via ``warmup``; on it otherwise, counted in ``aot_compiles``), and
every execution credits the measured collective census — the
``collective_bytes_total`` / ``solve_collective_bytes_total`` counters
move per served solve, not per compile. The LRU budget becomes
**per-chip**: a sharded resident is charged its max-per-shard bytes
and the transient term is the largest analyzed program's per-device
temp+output footprint (XLA's memory analysis describes the per-device
SPMD module), so ``hbm_budget`` bounds what the worst chip holds.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Hashable, List, Optional, Tuple

import jax
import numpy as np

from .. import api
from ..core.exceptions import SlateError
from ..core.grid import ProcessGrid, as_grid
from ..core.tiled_matrix import TiledMatrix, from_dense
from ..core.types import MatrixKind, Options, DEFAULT_OPTIONS
from ..linalg.band_packed import PackedBand
# model-GFLOP formulas live in the ledger (obs/flops.py) — one home
# shared with bench.py and tester.py instead of a private copy here
from ..obs import flops as _flops_mod
from ..obs.flops import LEDGER as _LEDGER
from ..obs.flops import factor_flops as _ff_raw
from ..obs.flops import solve_flops as _sf_raw
from ..obs import costs as _costs
# tenant/handle attribution (round 15): the grid snappers run
# UNCONDITIONALLY at the metric seams — model-flop counters land on
# the integer grid whether or not a ledger is attached, so enabling
# attribution never changes a global counter and the per-tenant rows
# sum to the globals bit-exactly (obs/attribution.py module docstring)
from ..obs.attribution import (DEFAULT_TENANT, PLACEMENT_SCHEMA,
                               fl_grid as _fl_grid, s_grid as _s_grid,
                               validate_placement_snapshot)
from ..obs.tracing import Tracer, default_tracer, log as _obs_log
# numerical-health telemetry (round 16): growth bounds, the
# Hager-Higham condest loop, the deterministic residual sampler, and
# the per-handle health monitor — jax-free; the Session drives it with
# resident-factor solve applies at its existing program seams
from ..obs import numerics as _num
from ..refine import engine as _refine_engine
from ..refine.policy import PolicyTable, RefinePolicy
from .metrics import Metrics
from .tenancy import as_table as _as_tenant_table


def _factor_flops(op: str, m: int, n: int, band: int = 0) -> float:
    """Model factor flops snapped to the integer grid (obs/attribution:
    exact float accumulation -> the per-tenant conservation invariant
    is bit-exact by arithmetic). <1e-13 relative change vs the raw
    lawn41 formula; every serving counter seam uses this wrapper."""
    return _fl_grid(_ff_raw(op, m, n, band))


def _solve_flops(op: str, m: int, n: int, k: int, band: int = 0) -> float:
    """Model solve flops on the integer grid (see _factor_flops)."""
    return _fl_grid(_sf_raw(op, m, n, k, band))


# operator kinds a Session can keep resident. The *_small family
# (round 10) is the many-small-problems engine: dense [n, n] ARRAY
# operators served through the hand-batched blocked kernels
# (linalg/batched) — the per-request path runs the SAME kernels at
# B=1 that the Batcher's grouped dispatch runs at B=bucket, so the
# batched and per-request paths are bit-identical by construction
# (batch-independent arithmetic, pinned by tests/test_batched.py).
OPS = ("lu", "chol", "qr", "band_lu", "band_chol",
       "lu_small", "chol_small", "eig", "svd")
SMALL_OPS = ("lu_small", "chol_small")
# resident spectral operators (round 19, slate_tpu/spectral/): the
# factor is the staged two-stage decomposition, the "solve" is the
# served matrix-function apply (two analyzed gemms + a diagonal scale)
SPECTRAL_OPS = ("eig", "svd")
# operators the round-16 condest probe covers (the gecondest/pocondest
# driver families; QR serves least-squares — trcondest on R is a
# different estimate — and band factors stay on the eager verbs)
CONDEST_OPS = ("lu", "chol", "lu_small", "chol_small")
# operators the sampled residual probe covers: b − A·x is an error
# signal only where x solves A·x = b (a least-squares minimizer's
# residual is data, not error)
PROBE_OPS = ("lu", "chol")
# operators the round-20 incremental-maintenance verb covers: rank-k
# Cholesky up/downdates (dense + small-engine residents) and QR row
# append/delete (linalg/update.py). Everything else answers a mutation
# with the refactor it always did.
UPDATE_OPS = ("chol", "chol_small", "qr")


def _work_dtype_name(entry) -> str:
    """Canonical working-dtype name of a registered operator (the
    refine/policy vocabulary the numerics thresholds scale by) — as
    the DEVICE computes it: without jax x64, a float64-registered
    small operand truly solves in float32, and scaling the residual
    thresholds by float64's eps would flag every healthy handle
    suspect (found by the obs_dump smoke, which runs without x64)."""
    from ..refine.policy import canonical_dtype_name
    A = entry.A
    dt = A.ab.dtype if isinstance(A, PackedBand) else A.dtype
    return canonical_dtype_name(jax.dtypes.canonicalize_dtype(dt))


def _tree_nbytes(payload, per_chip: bool = False) -> int:
    """Device bytes held by a factor payload (sum over pytree leaves).

    Computed from shape/dtype metadata ONLY: the old
    ``np.asarray(leaf).nbytes`` fallback device-transferred any leaf
    lacking ``.nbytes`` — a full factor copy through the host on the
    cache-accounting path (pinned by test: no ``__array__`` call).

    ``per_chip=True`` (round 11) charges a SHARDED leaf its
    max-per-shard bytes — ``sharding.shard_shape`` is pure metadata,
    and GSPMD shards are even, so the max shard is any shard — which
    is the number the per-chip HBM budget must bound. Unsharded (or
    fully replicated) leaves charge their full bytes on every chip,
    which is exactly what replication costs."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(payload):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            if per_chip:
                sharding = getattr(leaf, "sharding", None)
                shard_shape = getattr(sharding, "shard_shape", None)
                if shard_shape is not None:
                    try:
                        shape = shard_shape(tuple(shape))
                    except Exception:
                        pass  # charge the full (replicated) bytes
            n = 1
            for d in shape:
                n *= int(d)
            total += n * np.dtype(dtype).itemsize
        elif getattr(leaf, "nbytes", None) is not None:
            total += int(leaf.nbytes)
        else:  # python scalar leaf: its device form is one element
            total += np.dtype(type(leaf)).itemsize if isinstance(
                leaf, (int, float, complex)) else 0
    return total


@dataclasses.dataclass
class _Operator:
    """A registered (not necessarily factored) operator."""

    A: Any                   # TiledMatrix or PackedBand
    op: str
    opts: Options
    m: int
    n: int
    band: int = 0            # kl+ku (band ops) for flop accounting
    # serving mesh (round 11): dense operators registered against a
    # multi-device grid are factored/solved as sharded AOT programs
    # and their residents charged per-chip; None = single-device
    grid: Optional[ProcessGrid] = None
    # mixed-precision refinement (round 13, slate_tpu/refine/): the
    # resident factor is computed/stored at policy.factor_dtype and
    # every solve refines to working accuracy; None = full precision.
    # Cleared (with the lo resident evicted) when refinement falls
    # back — the counted, observable non-convergence path.
    refine: Optional[RefinePolicy] = None
    # ‖A‖_inf, computed once at first refined solve (the convergence
    # constant's norm — gesv_mixed.cc:34-43)
    anorm: Optional[float] = None
    # ‖A‖_1, computed once at the first condest probe (round 16 —
    # Hager's estimator reports ‖A⁻¹‖_1, so κ̂₁ needs the 1-norm)
    anorm1: Optional[float] = None
    # attribution tenant (round 15): who this operator belongs to.
    # None = the DEFAULT_TENANT — every existing caller lands there,
    # so single-tenant deployments get the ledger without changes
    tenant: Optional[str] = None
    # incremental-maintenance accrual (round 20): applied-update count
    # and growth-weighted error mass since the last fresh factor — the
    # monitor-less fallback for the refactor-due predicate (a numerics
    # monitor, when attached, keeps the authoritative copy per handle).
    # Reset by every fresh factor insert.
    updates: int = 0
    update_weight: float = 0.0
    # tuned-config provenance (round 21): the tuning-table entry (or
    # shadow-tuner promotion) whose knobs this operator's opts carry —
    # the `tuned_config` span attr / cost_log column. None = defaults.
    tuned: Optional[str] = None


@dataclasses.dataclass
class _Resident:
    """A cached factorization (the HBM the LRU budget governs).

    ``nbytes`` is the BUDGET CHARGE: per-chip bytes (max-per-shard for
    mesh residents — the worst chip's share; equal to the total on a
    single device). ``nbytes_total`` is the aggregate bytes across the
    mesh, kept for the ``resident_bytes_total`` gauge."""

    payload: Tuple           # args for the *_solve_using_factor verb
    info: int
    nbytes: int
    nbytes_total: int = 0

    def __post_init__(self):
        if not self.nbytes_total:
            self.nbytes_total = self.nbytes


class Session:
    """Resident-factorization solve service with an HBM-budget LRU cache.

    ``hbm_budget`` bounds the PER-CHIP device bytes of CACHED FACTORS
    (the registered operators themselves are the caller's inputs and
    are not charged): a mesh resident is charged its max-per-shard
    bytes, a single-device resident its full bytes — identical when
    there is no mesh, so the budget means "what the worst chip holds"
    uniformly. ``None`` means unbounded. Factors are built lazily on
    the first solve (refactor-on-miss) and evicted least-recently-used
    when an insert would exceed the budget; a single factor larger than
    the whole budget is kept (you cannot serve without it) and counted
    in the ``budget_overflows`` metric.

    All public methods are thread-safe; solve dispatch is serialized
    under one lock (the device executes one program at a time anyway —
    the batcher, not thread fan-out, is the throughput lever).
    """

    def __init__(self, hbm_budget: Optional[int] = None,
                 opts: Options = DEFAULT_OPTIONS,
                 metrics: Optional[Metrics] = None,
                 tracer: Optional[Tracer] = None,
                 mesh=None, slo=None,
                 refine_policies: Optional[PolicyTable] = None,
                 faults=None, attribution=None, numerics=None,
                 checkpoint_dir: Optional[str] = None,
                 tenant_policies=None, tuning=None):
        self.hbm_budget = hbm_budget
        # autotuning table (round 21, slate_tpu/tuning/): a
        # TuningTable / loaded doc / path, or True for the committed
        # repo-root TUNING_r01.json. None = disabled — every
        # consultation seam is ONE `tuning is None` check and with no
        # table every solve is bit-identical to an untuned session
        # (pinned). register() resolves each operator's
        # nb/inner_blocking/lookahead through the table by first-match
        # (op, n-bucket, dtype, platform); the resolved provenance
        # rides span attrs and the cost_log as `tuned_config`. A
        # session-held table is also ACTIVATED process-globally for
        # the linalg/batched bucket cache (its programs are
        # process-global, so its tuning seam is too — last activation
        # wins; tuning.activate_table(None) restores defaults).
        from .. import tuning as _tuning_mod
        self.tuning = _tuning_mod.as_table(tuning)
        if self.tuning is not None:
            _tuning_mod.activate_table(self.tuning)
        # tenant isolation (round 18, runtime/tenancy.py): a
        # TenantTable (or {tenant: TenantPolicy} dict) declaring
        # per-tenant HBM sub-budgets (enforced here at the
        # factor-insert seam with per-tenant LRU eviction — tenant A's
        # pressure can NEVER evict tenant B's resident, pinned),
        # in-flight caps and flops/s rates (enforced at
        # Batcher.submit), and fair-share weights (the Batcher's
        # deficit-weighted dispatch). None = disabled: every seam is
        # one is-None check, zero allocation (the round-8 discipline,
        # pinned by test).
        self.tenant_policies = _as_tenant_table(tenant_policies)
        # durable-state directory (round 17): when set, close() flushes
        # a final checkpoint (runtime/checkpoint.py) + placement
        # snapshot there — the artifact the fleet coordinator's
        # failover restores from after this process dies. None = the
        # pre-round-17 behavior (close drops resident state).
        self.checkpoint_dir = checkpoint_dir
        # numerical-health telemetry (round 16): None = disabled —
        # every seam guards with ONE `numerics is None` check and
        # allocates nothing (the round-8 tracer discipline, pinned by
        # test). An obs.numerics.NumericsMonitor tracks per-handle
        # condest / growth / sampled-residual / refine-drift signals
        # into a healthy/degraded/suspect state with counted reflexes
        # (suspect handles are demoted off the refine ladder and lose
        # eviction tie-breaks — never silently).
        self.numerics = numerics
        # tenant/handle attribution (round 15): None = disabled — every
        # seam guards with ONE `attr is None` check and allocates
        # nothing (the round-8 tracer discipline, pinned by test). An
        # obs.attribution.AttributionLedger accounts flops, bytes, ICI
        # bytes, device/queue seconds, HBM residency byte-seconds,
        # cache hits/misses, and request outcomes per (tenant, handle),
        # plus EWMA handle heat — the placement/quota sensing substrate
        self.attribution = attribution
        # deterministic fault injection (round 14): None = disabled —
        # every seam guards with ONE `faults is None` check, so the
        # production hot path pays nothing (the round-8 tracer
        # discipline, pinned by test). A runtime/faults.FaultInjector
        # makes dispatch failures, slow devices, compile stalls, HBM
        # exhaustion, and refine non-convergence reproducible inputs.
        self.faults = faults
        # flight recorder + decision journal (round 22,
        # obs/recorder.py): None = disabled — every reflex seam guards
        # with ONE `recorder is None` check and allocates nothing (the
        # round-8 discipline, pinned by test). enable_recorder() is
        # the opt-in; every counted reflex decision then also lands a
        # structured DecisionEvent (events.KIND_COUNTERS parity,
        # pinned), and anomaly/breach/breaker/fault transitions
        # capture rate-limited incident snapshots.
        self.recorder = None
        # telemetry history (round 23, obs/timeseries.py): None =
        # disabled — the pump seam guards with ONE `timeseries is
        # None` check and allocates nothing (the round-8 discipline,
        # pinned by test). enable_timeseries() attaches the bounded
        # time-series store, the pump()-style sampler (thread-free:
        # Fleet.pump / a chaos driver / a scrape loop calls
        # pump_timeseries on its own thread), and the forecaster
        # behind the /history and /forecast routes.
        self.timeseries = None
        self.forecaster = None
        self._ts_sampler = None
        self.opts = opts
        # mixed-precision policy table (round 13): register(...,
        # refine=True) resolves its RefinePolicy here per
        # (op, n, working dtype); the default table falls back to the
        # one-tier-down dtype ladder (refine/policy.py)
        self.refine_policies = refine_policies or PolicyTable()
        # serving mesh: a ProcessGrid or a jax Mesh with ("p", "q")
        # axes; every dense operator registered without an explicit
        # per-operator mesh is sharded over it (mesh docstring above).
        # With a mesh, hbm_budget bounds PER-CHIP bytes.
        self.grid = as_grid(mesh)
        self.metrics = metrics or Metrics()
        if attribution is not None and attribution.metrics is None:
            attribution.metrics = self.metrics  # heat gauges land here
        if numerics is not None and numerics.metrics is None:
            numerics.metrics = self.metrics  # health gauges land here
        # request-scoped tracing: disabled by default (the shared
        # default tracer starts off) — zero spans, no per-solve cost
        # beyond one enabled-flag check per phase
        self.tracer = tracer or default_tracer()
        # SLO tracking (round 12): None = disabled, zero per-solve cost
        # beyond one attribute check (the round-8 discipline); an
        # obs.slo.SloTracker records request/cache/oom events here and
        # through the Batcher, evaluated at /slo scrape time
        self.slo = slo
        if slo is not None and slo.metrics is None:
            slo.metrics = self.metrics
        if slo is not None and slo.tracer is None:
            slo.tracer = self.tracer
        # per-shape compile observability (Session.warmup + refactor-on-
        # miss): [{op, what, shape, lower_s, compile_s}, ...]
        self.compile_log: List[dict] = []
        # per-shape COST observability (ISSUE 5): one row per AOT-
        # compiled program — model flops, XLA bytes-accessed, arg/out/
        # temp/peak HBM, collective census (obs/costs.py)
        self.cost_log: List[dict] = []
        # (op, what) -> newest model_flops row, maintained as cost_log
        # grows: the shed-ordering read (recompute_cost) runs under
        # the Batcher's queue lock per queued request and must be O(1),
        # not a cost_log scan
        self._cost_index: Dict[Tuple[str, str], float] = {}
        # AOT-key -> ProgramCosts for resident executables; drives the
        # per-execution bytes crediting and the transient-footprint
        # term of the HBM budget (evicted in step with _compiled)
        self._program_costs: Dict[Hashable, _costs.ProgramCosts] = {}
        self._obs_server = None
        self._lock = threading.RLock()
        self._ops: Dict[Hashable, _Operator] = {}
        self._cache: "OrderedDict[Hashable, _Resident]" = OrderedDict()
        # per-(op, opts) jitted solve fns and per-shape AOT executables;
        # both LRU-capped: compiled programs hold device memory, and a
        # long-lived session serving many distinct shapes would
        # otherwise re-grow the unbounded-residency problem the factor
        # budget bounds (evicted entries simply recompile on reuse)
        self._jit: "OrderedDict[Hashable, Any]" = OrderedDict()
        self._compiled: "OrderedDict[Hashable, Any]" = OrderedDict()
        self._jit_cap = 64
        self._compiled_cap = 128
        self._seq = 0

    def enable_slo(self, objectives=None, **kw):
        """Attach an :class:`~..obs.slo.SloTracker` (default
        objectives unless given) bound to this session's metrics and
        tracer; idempotent — a second call returns the running tracker.
        The ``/slo`` route of :meth:`serve_obs` serves its payload."""
        from ..obs.slo import SloTracker
        with self._lock:
            if self.slo is None:
                self.slo = SloTracker(objectives, metrics=self.metrics,
                                      tracer=self.tracer, **kw)
                if self.recorder is not None:
                    # breach transitions are incident triggers (rd 22)
                    self.slo.recorder = self.recorder
            return self.slo

    def enable_attribution(self, halflife_s: float = 300.0, **kw):
        """Attach an :class:`~..obs.attribution.AttributionLedger`
        (heat halflife ``halflife_s``) bound to this session's metrics
        and return it; idempotent — a second call returns the running
        ledger. The ``/tenants`` route of :meth:`serve_obs` serves its
        payload and ``/metrics`` grows the ``tenant_*`` sections."""
        from ..obs.attribution import AttributionLedger
        with self._lock:
            if self.attribution is None:
                self.attribution = AttributionLedger(
                    halflife_s=halflife_s, metrics=self.metrics, **kw)
            return self.attribution

    def enable_numerics(self, config=None, **kw):
        """Attach an :class:`~..obs.numerics.NumericsMonitor` (round
        16) bound to this session's metrics and return it; idempotent
        — a second call returns the running monitor. ``config`` is a
        :class:`~..obs.numerics.NumericsConfig` (or kwargs for one:
        ``sample_fraction=``, thresholds, ...). The ``/numerics``
        route of :meth:`serve_obs` serves its payload and ``/metrics``
        grows the ``handle_health`` gauges."""
        from ..obs.numerics import NumericsMonitor
        with self._lock:
            if self.numerics is None:
                self.numerics = NumericsMonitor(
                    config, metrics=self.metrics, **kw)
            return self.numerics

    def request_tenant(self, handle: Hashable,
                       override: Optional[str] = None) -> str:
        """Resolved tenant of one request: the explicit per-request
        override, else the operator's registered tenant, else the
        DEFAULT_TENANT. Lock-free (the op_meta discipline: called from
        the Batcher's outcome paths, which must never wait on a device
        execution)."""
        if override is not None:
            return str(override)
        entry = self._ops.get(handle)
        t = None if entry is None else entry.tenant
        return DEFAULT_TENANT if t is None else t

    def _attr_evicted(self, handle: Hashable):
        """Caller verified ``self.attribution is not None``. Close the
        handle's residency interval (final byte-second accrual credits
        the same value to the cell and the global counter) and advance
        its heat (decay only — an eviction is not an access)."""
        attr = self.attribution
        inc = attr.end_residency(handle)
        if inc:
            self.metrics.inc("residency_byte_seconds_total", inc)
        attr.touch_eviction(handle)

    def _journal_evict(self, rec, handle, nbytes, reason,
                       entry=None, **inputs):
        """Caller verified ``rec`` (= self.recorder) is not None: ONE
        reason-tagged eviction DecisionEvent — every seam that bumps
        the ``evictions`` counter funnels here, so the journal/counter
        parity per events.KIND_COUNTERS stays exact."""
        if entry is None:
            entry = self._ops.get(handle)
        rec.decision("eviction",
                     op=None if entry is None else entry.op,
                     handle=handle,
                     tenant=None if entry is None else entry.tenant,
                     outcome=reason,
                     inputs=dict(inputs, nbytes=nbytes))

    def enable_faults(self, plan=None, seed: int = 1):
        """Attach a :class:`~.faults.FaultInjector` built from ``plan``
        (default: :func:`~.faults.default_plan` under ``seed``) and
        return it — the chaos runner's entry point. Idempotent in the
        enable_slo sense: a second call replaces the injector (a new
        soak wants fresh counters)."""
        from .faults import FaultInjector, FaultPlan, default_plan
        if plan is None:
            plan = default_plan(seed)
        elif isinstance(plan, dict):
            plan = FaultPlan.from_dict(plan)
        self.faults = FaultInjector(plan)
        if self.recorder is not None:
            # injector firings are incident triggers (round 22)
            self.faults.recorder = self.recorder
        return self.faults

    def enable_recorder(self, incident_dir: Optional[str] = None,
                        host: Optional[str] = None, **kw):
        """Attach an :class:`~..obs.recorder.Recorder` (round 22): the
        decision journal + flight recorder + incident capture, bound
        to this session's metrics and tracer; idempotent — a second
        call returns the running recorder. ``incident_dir`` enables
        crash-safe on-disk incident snapshots (atomic publish);
        ``kw`` forwards ring capacities and rate-limit/dedup windows.
        The ``/journal`` and ``/incidents`` routes of
        :meth:`serve_obs` serve its payloads."""
        from ..obs.recorder import Recorder
        with self._lock:
            if self.recorder is None:
                rec = Recorder(incident_dir=incident_dir, host=host,
                               metrics=self.metrics,
                               tracer=self.tracer, **kw)
                rec.providers.update({
                    "metrics": self.metrics.snapshot,
                    "numerics": self.numerics_payload,
                    "quotas": self.quotas_payload,
                    "placement": self.placement_snapshot,
                    # the newest rows carry the implicated programs'
                    # compile provenance; the full log stays on /costs
                    "cost_log": lambda: list(self.cost_log[-64:]),
                    "tuning": self._tuning_provenance,
                })
                # finished spans feed the flight ring (tracing hook)
                self.tracer.recorder = rec
                if self.faults is not None:
                    self.faults.recorder = rec
                if self.slo is not None:
                    self.slo.recorder = rec
                self.recorder = rec
            return self.recorder

    def enable_timeseries(self, interval_s: float = 1.0,
                          clock=time.time, host: Optional[str] = None,
                          **kw):
        """Attach the telemetry-history layer (round 23): a bounded
        :class:`~..obs.timeseries.TimeseriesStore`, a ``pump()``-style
        :class:`~..obs.timeseries.SessionSampler` throttled to
        ``interval_s`` (drive it with :meth:`pump_timeseries`), and a
        :class:`~..obs.forecast.Forecaster` over the store; idempotent
        — a second call returns the running store. ``clock`` is
        injectable (chaos drills and tests run on a scripted clock —
        no sleeps). ``kw`` forwards ring capacities / tier widths /
        ``max_series``. The ``/history`` and ``/forecast`` routes of
        :meth:`serve_obs` serve the payloads."""
        from ..obs.forecast import Forecaster
        from ..obs.timeseries import SessionSampler, TimeseriesStore
        with self._lock:
            if self.timeseries is None:
                store = TimeseriesStore(host=host, clock=clock, **kw)
                self._ts_sampler = SessionSampler(
                    self, store, interval_s=interval_s)
                self.forecaster = Forecaster(store)
                self.timeseries = store
            return self.timeseries

    def pump_timeseries(self, now: Optional[float] = None,
                        force: bool = False) -> int:
        """One history-sampling pass (round 23): snapshot gauges (at
        their stamped timestamps), counter deltas, per-handle heat,
        and per-tenant burn rates into the store. Thread-free and
        throttled; returns samples recorded (0 when throttled or
        disabled). Disabled (the default) costs ONE is-None check."""
        if self.timeseries is None:
            return 0
        return self._ts_sampler.pump(now=now, force=force)

    def _tuning_provenance(self) -> dict:
        """Incident-capture section: which handles serve under which
        resolved/promoted config right now."""
        with self._lock:
            handles = {repr(h): e.tuned for h, e in self._ops.items()
                       if getattr(e, "tuned", None) is not None}
        return {"table": self.tuning is not None, "handles": handles}

    def _fault(self, site: str):
        """Apply one fault opportunity at ``site`` (caller verified
        ``self.faults is not None``): count what fired, sleep the
        latency-shaped kinds first (a slow-and-then-failing device
        sleeps before failing, like the real thing), then raise for
        ``dispatch_error``. Returns the fired specs so boolean seams
        (hbm, refine.lo_factor) can branch on truthiness."""
        from .faults import TransientDispatchError
        fired = self.faults.fire(site)
        for spec in fired:
            self.metrics.inc("faults_injected_total")
            self.metrics.inc("fault:" + spec.kind)
            if spec.latency_s:
                time.sleep(spec.latency_s)
        for spec in fired:
            if spec.kind == "dispatch_error":
                raise TransientDispatchError(
                    f"injected transient dispatch failure at {site!r}")
        return fired

    def recompute_cost(self, handle: Hashable, ncols: int = 1) -> float:
        """Model flops the fleet pays again if this request is SHED and
        the client retries — the load shedder's cheapest-first ordering
        key. Prefers the round-9 ``cost_log``'s per-program
        ``model_flops`` rows (what the AOT seam actually measured for
        this op); falls back to the ledger formulas for ops never
        compiled through it. A request against a RESIDENT factor costs
        one solve; a non-resident one costs factor + solve — so
        shedding prefers requests whose operators are still hot.
        Lock-free (GIL-atomic dict/list reads, the op_meta discipline):
        the Batcher calls this under its own lock and must never wait
        on a device execution."""
        entry = self._ops.get(handle)
        if entry is None:
            return 0.0
        cost = (self._logged_flops(entry.op, "solve")
                or _solve_flops(entry.op, entry.m, entry.n, max(ncols, 1),
                                entry.band))
        if handle not in self._cache:
            cost += (self._logged_flops(entry.op, "factor")
                     or _factor_flops(entry.op, entry.m, entry.n,
                                      entry.band))
        return cost

    def _logged_flops(self, op: str, what: str) -> float:
        """Newest cost_log model_flops row for (op, what), 0.0 when the
        op never compiled through the AOT seam. O(1): the index is
        maintained as _aot_compile appends rows (GIL-atomic dict read —
        this runs under the Batcher lock on the shed path)."""
        return self._cost_index.get((op, what), 0.0)

    def degrade_class(self, handle: Hashable) -> Optional[str]:
        """Which DEGRADATION_LADDER family a handle's serving path
        belongs to ("mesh" / "mixed" / "dense"), None for unknown
        handles. Grouped small buckets classify themselves (the
        Batcher's _SMALL key). Lock-free, op_meta discipline."""
        entry = self._ops.get(handle)
        if entry is None:
            return None
        if entry.grid is not None:
            return "mesh"
        if entry.refine is not None:
            return "mixed"
        return "dense"

    def demote_to_working_precision(self, handle: Hashable) -> bool:
        """The mixed→working_precision rung of the degradation ladder,
        walked by the Executor's circuit breaker AND (round 16) the
        numerics suspect reflex: deactivate the refine policy and
        evict the low-precision resident so the next solve refactors
        at working precision (the same observable fallback refine
        non-convergence takes — counted separately in
        ``refine_demotions_total``; a numerics-driven demotion
        additionally counts ``health_demotions_total``, so the three
        causes stay distinguishable)."""
        with self._lock:
            entry = self._ops.get(handle)
            if entry is None or entry.refine is None:
                return False
            entry.refine = None
            dropped = self._cache.pop(handle, None)
            if dropped is not None:
                self.metrics.inc("evictions")
                self.metrics.inc("evicted_bytes", dropped.nbytes)
                if self.attribution is not None:
                    self._attr_evicted(handle)
            self.metrics.inc("refine_demotions_total")
            rec = self.recorder
            if rec is not None:
                if dropped is not None:
                    rec.decision("eviction", op=entry.op, handle=handle,
                                 tenant=entry.tenant,
                                 outcome="refine_demotion",
                                 inputs={"nbytes": dropped.nbytes})
                rec.decision("refine_demotion", op=entry.op,
                             handle=handle, tenant=entry.tenant,
                             outcome="working_precision")
            self._update_hbm_gauges()
        _obs_log.warning(
            "degradation ladder: operator %r demoted to working "
            "precision", handle)
        return True

    # -- numerical health (round 16, obs/numerics.py) ----------------------

    def _health_reflex(self, entry: _Operator, handle: Hashable,
                       old: str, new: str):
        """Caller verified ``self.numerics is not None``. The counted
        reflexes on a health-state transition: a handle that turns
        SUSPECT while serving from a low-precision resident is demoted
        off the refine ladder (the round-14
        ``demote_to_working_precision`` rung — ``refine_demotions_total``
        moves, plus ``health_demotions_total`` so a numerics-driven
        demotion is distinguishable from a breaker-driven one). Suspect
        handles also lose eviction tie-breaks (:meth:`_eviction_order`).
        Never silent: the monitor already logged/counted the
        transition."""
        if new == old:
            return
        if new == "suspect" and entry.refine is not None:
            self.metrics.inc("health_demotions_total")
            rec = self.recorder
            if rec is not None:
                _st, condest, growth = \
                    self.numerics.placement_info(handle)
                rec.decision("health_demotion", op=entry.op,
                             handle=handle, tenant=entry.tenant,
                             inputs={"from": old, "to": new,
                                     "condest": condest,
                                     "growth": growth},
                             outcome="suspect")
            _obs_log.warning(
                "numerics reflex: suspect operator %r demoted off the "
                "refine ladder", handle)
            self.demote_to_working_precision(handle)

    def condest(self, handle: Hashable) -> float:
        """Hager-Higham 1-norm condition estimate κ̂₁(A) ≈ ‖A‖₁‖A⁻¹‖₁
        from the RESIDENT factor (factoring on miss) — the serving
        analog of slate::gecondest/pocondest (LAPACK ``?gecon``): a
        handful of extra ``*_solve_using_factor`` applies driven by
        :func:`~..obs.numerics.norm1est`, each executing the SAME
        analyzed AOT solve programs the serving path runs (mesh
        residents included — zero new compiles after :meth:`warmup`),
        credited per execution to the cost/attribution ledgers under
        the ``numerics.condest`` op. Covers lu/chol operators (dense —
        single-device or mesh-sharded — and the *_small engine).
        Records into the attached NumericsMonitor (if any) and runs
        the health reflexes on the resulting transition."""
        with self._lock:
            entry = self._ops.get(handle)
            if entry is None:
                raise SlateError(f"Session: unknown handle {handle!r}")
            if entry.op not in CONDEST_OPS:
                raise SlateError(
                    f"Session.condest: covers {CONDEST_OPS}, not "
                    f"{entry.op!r}")
            nm = self.numerics
            hit = handle in self._cache
            res = self.factor(handle)
            if res.info != 0:
                raise SlateError(
                    f"Session.condest: operator {handle!r} factorization "
                    f"failed (info={res.info})")
            if (not hit and nm is not None
                    and nm.config.condest_on_factor):
                # the factor-on-miss just ran the estimator at its own
                # seam (_numerics_after_factor) — return that estimate
                # instead of paying the probe solves twice for one
                # logical question
                ce = nm.placement_info(handle)[1]
                if ce is not None:
                    return ce
            # a factor-time health reflex may have demoted + refactored
            # (the returned res IS the serving resident either way)
            return self._condest_locked(entry, handle, res)

    def _condest_locked(self, entry: _Operator, handle: Hashable,
                        res: _Resident) -> float:
        """Caller holds the lock; ``res`` is a successful resident."""
        nm = self.numerics
        cfg = nm.config if nm is not None else _num.NumericsConfig()
        n = entry.n
        if entry.anorm1 is None:
            if entry.op in SMALL_OPS:
                a = np.asarray(entry.A)
                entry.anorm1 = float(
                    np.abs(a.astype(np.complex128 if np.iscomplexobj(a)
                                    else np.float64)).sum(axis=0).max())
            else:
                from ..core.types import Norm
                from ..linalg.norms import norm as _norm
                entry.anorm1 = float(_norm(entry.A, Norm.One))
        wd = _work_dtype_name(entry)
        cplx = wd.startswith("complex")
        solve, solve_h = self._condest_applies(entry, handle, res, cplx)
        est, solves = _num.norm1est(solve, solve_h, n, complex_=cplx,
                                    max_iter=cfg.condest_max_iter)
        cond = (float("inf") if est <= 0.0 or entry.anorm1 <= 0.0
                else entry.anorm1 * est)
        if not np.isfinite(cond):
            # the session-level sentinel counter must agree with the
            # per-handle nonfinite field record_condest bumps below
            self.metrics.inc("numerics_nonfinite_total")
        # probe-work crediting: `solves` factor applies of one column
        # each — the model-flop seam every serving counter uses, on a
        # dedicated counter/ledger op so client-attributed solve work
        # stays conserving (numerics probes are system work)
        fl = solves * _solve_flops(entry.op, entry.m, entry.n, 1,
                                   entry.band)
        self.metrics.inc("condest_runs_total")
        self.metrics.inc("condest_solves_total", solves)
        self.metrics.inc("numerics_flops_total", fl)
        self.metrics.inc("flops_total", fl)
        _LEDGER.record("numerics.condest", fl)
        if nm is not None:
            old, new = nm.record_condest(handle, cond)
            self._health_reflex(entry, handle, old, new)
        return cond

    def _condest_applies(self, entry: _Operator, handle: Hashable,
                         res: _Resident, cplx: bool):
        """Caller holds the lock. (x ↦ A⁻¹x, x ↦ A⁻ᴴx) host callables
        over the resident factor for :func:`~..obs.numerics.norm1est`
        (np [n, 1] float64/complex128 in and out).

        Dense operators run the SAME solve programs the serving path
        uses (warmup-compiled AOT executables when shapes match — the
        mesh zero-new-compiles claim; refined residents apply through
        the refine ``start`` program, i.e. cast-down → lo factor apply
        → cast-up, so the estimate describes the factor that actually
        serves). LU adds one conjugate-transpose-solve program
        (``condest_t``), compiled through the analyzed AOT seam.
        Small operators run their B=1 bucket programs
        (accounting-suppressed — the condest seam credits explicitly);
        the lu_small transpose solve runs host-side from a one-time
        factor gather (triangular solves at small n)."""
        op = entry.op
        payload = res.payload
        tenant = entry.tenant

        if op in SMALL_OPS:
            from ..linalg import batched as _batched
            if op == "chol_small":
                lfac = payload[0]

                def apply(x):
                    with _batched.suppress_accounting():
                        y = _batched.potrs_batched(
                            lfac[None], np.ascontiguousarray(x)[None])
                    return np.asarray(jax.block_until_ready(y))[0]

                # A⁻ᴴ = A⁻¹ for an HPD operator (pocondest: one solver)
                return apply, apply
            lu_d, perm_d = payload

            def apply(x):
                with _batched.suppress_accounting():
                    y = _batched.getrs_batched(
                        lu_d[None], perm_d[None],
                        np.ascontiguousarray(x)[None])
                return np.asarray(jax.block_until_ready(y))[0]

            # host conjugate-transpose solve from the gathered factor:
            # a[perm] = L·U (gather semantics, linalg/batched), so
            # A⁻ᴴx = Pᵀ·L⁻ᴴ·U⁻ᴴ·x — scatter rows back through perm
            work = np.complex128 if cplx else np.float64
            lu_h = np.asarray(lu_d).astype(work)
            perm_h = np.asarray(perm_d).astype(np.int64)
            nloc = lu_h.shape[0]
            l_h = np.tril(lu_h, -1) + np.eye(nloc)
            u_h = np.triu(lu_h)

            def apply_h(x):
                w = np.linalg.solve(u_h.conj().T, x)
                v = np.linalg.solve(l_h.conj().T, w)
                y = np.zeros_like(v)
                y[perm_h] = v
                return y

            return apply, apply_h

        # dense lu/chol (single-device, mesh-sharded, or refined)
        def host(X):
            return (X.to_numpy() if isinstance(X, TiledMatrix)
                    else np.asarray(X))

        if entry.refine is not None:
            def fwd(x):
                B = self._wrap_rhs(entry, np.ascontiguousarray(x))
                exe, key = self._refine_exe(entry, handle, "start",
                                            (payload, B))
                X = exe(payload, B)
                self._credit_program(key, "numerics.condest",
                                     tenant=tenant, handle=handle)
                return host(X)
        else:
            solve_fn = self._solve_fn(entry)

            def fwd(x):
                B = self._wrap_rhs(entry, np.ascontiguousarray(x))
                key = self._aot_key(entry, payload, B)
                exe = self._compiled.get(key)
                if exe is None and entry.grid is not None:
                    exe = self._aot_compile("solve", entry, handle,
                                            solve_fn, (payload, B),
                                            key=key)
                    self._compiled_put(key, exe)
                    self.metrics.inc("aot_compiles")
                if exe is not None:
                    self._compiled.move_to_end(key)
                    self._credit_program(key, "numerics.condest",
                                         tenant=tenant, handle=handle)
                    return host(exe(payload, B))
                return host(solve_fn(payload, B))

        if op == "chol":
            # A⁻ᴴ = A⁻¹ (HPD resident) — the pocondest convention
            return fwd, fwd

        def tsolve(x):
            xq = np.conj(x) if cplx else x
            B = self._wrap_rhs(entry, np.ascontiguousarray(xq))
            exe, key = self._condest_texe(entry, handle, payload, B)
            Y = exe(payload, B)
            if key is not None:
                self._credit_program(key, "numerics.condest",
                                     tenant=tenant, handle=handle)
            y = host(Y)
            return np.conj(y) if cplx else y

        return fwd, tsolve

    def _condest_tfn(self, entry: _Operator):
        """The LU conjugate-transpose-solve closure (x ↦ A⁻ᵀx via
        ``getrs(..., trans=True)``; the host wrapper conjugates around
        it for complex dtypes). Refined residents cast the rhs down to
        the factor dtype and the result back up, mirroring the refine
        ``start`` program — the estimate must describe the factor that
        serves."""
        opts = entry.opts
        if entry.refine is not None:
            policy = entry.refine
            work = entry.A.dtype

            def make():
                from ..linalg import elementwise as ew
                from ..linalg.lu import getrs as _getrs
                from ..refine.policy import jax_dtype as _jd
                lo = _jd(policy.factor_dtype)

                def tsolve(payload, B):
                    LU, perm = payload
                    Y = _getrs(LU, perm, ew.copy(B, dtype=lo), opts,
                               trans=True)
                    return ew.copy(Y, dtype=work)
                tsolve.__name__ = "serve_lu_condest_t_refined"
                return tsolve

            return self._jit_cached(
                ("condest_t", entry.op, opts, policy,
                 str(np.dtype(entry.A.dtype))), make)

        def make():
            from ..linalg.lu import getrs as _getrs

            def tsolve(payload, B):
                LU, perm = payload
                return _getrs(LU, perm, B, opts, trans=True)
            tsolve.__name__ = "serve_lu_condest_t"
            return tsolve

        return self._jit_cached(("condest_t", entry.op, opts), make)

    def _condest_texe(self, entry: _Operator, handle: Hashable,
                      payload, B):
        """AOT-compiled ``condest_t`` program for these shapes →
        (exe, key) — always through the analyzed ``_aot_compile`` seam
        (the _refine_exe discipline: per-execution bytes/census
        crediting; warmup precompiles it so a warmed operator's
        condest adds zero compiles)."""
        leaves, treedef = jax.tree_util.tree_flatten((payload, B))
        shapes = tuple((tuple(l.shape), str(l.dtype)) for l in leaves)
        key = ("condest_t", entry.op, entry.opts, entry.refine, treedef,
               shapes)
        exe = self._compiled.get(key)
        if exe is None:
            fn = self._condest_tfn(entry)
            exe = self._aot_compile("condest_t", entry, handle, fn,
                                    (payload, B), key=key)
            self._compiled_put(key, exe)
            self.metrics.inc("aot_compiles")
        else:
            self._compiled.move_to_end(key)
        return exe, key

    def op_meta(self, handle: Hashable) -> Optional[Tuple[str, int]]:
        """Lock-free (op, n) of a registered handle, or None — the
        Batcher/Executor SLO- and stage-attribution read (same
        GIL-atomic dict-read discipline as ``small_group_key``: the
        session lock is held across device executions, and an enqueue
        must never wait on one)."""
        entry = self._ops.get(handle)
        return None if entry is None else (entry.op, entry.n)

    # -- registration ------------------------------------------------------

    def register(self, A, op: str = "auto",
                 handle: Optional[Hashable] = None,
                 opts: Optional[Options] = None,
                 mesh=None, refine=None,
                 tenant: Optional[str] = None) -> Hashable:
        """Register an operator; returns its handle (auto-allocated int
        when not given). ``op``: one of {lu, chol, qr, band_lu,
        band_chol} or "auto" (PackedBand → band_*, Hermitian/Symmetric
        → chol, rectangular → qr, else lu).

        ``mesh`` (a ProcessGrid or ("p", "q") jax Mesh) places THIS
        operator on a grid, overriding the session mesh in BOTH
        directions — an explicit 1×1 grid registers the operator
        single-device on a mesh session. A dense TiledMatrix is
        2D-block sharded over the grid at registration and its factor
        stays mesh-resident (module docstring). An operand that
        already carries a multi-device grid is served mesh-native
        without any mesh argument.

        ``tenant`` (round 15): who this operator belongs to — every
        counter class the attribution ledger accounts (flops, bytes,
        seconds, residency byte-seconds, outcomes) and the operator's
        handle heat attribute here. ``None`` (every existing caller)
        lands on the DEFAULT_TENANT; per-request overrides ride the
        ``tenant=`` kwarg of solve/Batcher.submit/Executor.submit.

        ``refine`` (round 13): a :class:`~..refine.RefinePolicy`, or
        ``True`` to resolve one from the session's policy table per
        (op, n, working dtype). The resident factor is then computed
        AND STORED at ``policy.factor_dtype`` (a bf16-from-f32
        resident charges ~half the budget — ~2× residents per HBM
        byte) and every solve refines to working-precision accuracy
        through the ``refine/`` engine; non-convergence falls back to
        a working-precision refactor, counted in
        ``refine_fallbacks_total``. Covers lu/chol operators (dense —
        single-device or mesh-sharded — and the *_small batched
        engine); GMRES-IR strategy is single-device dense only."""
        if op == "auto":
            op = self._infer_op(A)
        if mesh is not None:
            # explicit per-operator override; as_grid maps a 1×1 grid
            # to None = explicit single-device placement
            grid = as_grid(mesh)
        else:
            grid = self.grid
            if grid is None and isinstance(A, TiledMatrix):
                grid = A.grid if (A.grid is not None
                                  and A.grid.size > 1) else None
        if grid is not None:
            if op not in ("lu", "chol", "qr", "eig", "svd"):
                raise SlateError(
                    f"Session.register: mesh serving covers the dense "
                    f"operator kinds (lu/chol/qr/eig/svd), not {op!r}")
            if not isinstance(A, TiledMatrix):
                raise SlateError(
                    "Session.register: mesh serving requires a "
                    f"TiledMatrix operand, got {type(A).__name__}")
            if A.grid is not grid or A.data.shape[0] % (grid.p * A.nb) \
                    or A.data.shape[1] % (grid.q * A.nb):
                # 2D-block placement over the mesh (NamedSharding; the
                # BaseMatrix tileRank analog — core/grid.py): the
                # registered operand itself is mesh-resident, so the
                # factor program reads sharded inputs
                A = A.shard(grid)
        if op not in OPS:
            raise SlateError(f"Session.register: unknown op {op!r}")
        # operand/op agreement, checked here so a mismatch fails at
        # registration, not on the first request-path solve
        if (op in ("band_lu", "band_chol")) != isinstance(A, PackedBand):
            raise SlateError(
                f"Session.register: op {op!r} requires a "
                f"{'PackedBand' if op.startswith('band') else 'TiledMatrix'}"
                f" operand, got {type(A).__name__}")
        if (op in SMALL_OPS) != (not isinstance(A, PackedBand)
                                 and not hasattr(A, "kind")):
            raise SlateError(
                f"Session.register: op {op!r} requires a "
                f"{'plain dense [n, n] array' if op in SMALL_OPS else 'TiledMatrix'}"
                f" operand, got {type(A).__name__}")
        if isinstance(A, PackedBand):
            m = n = A.n
            band = A.kl + A.ku
        else:
            m, n = A.shape
            band = 0
        if op in SMALL_OPS:
            if m != n:
                raise SlateError(
                    "Session.register: small-problem operators must be "
                    f"square, got {(m, n)}")
            A = np.ascontiguousarray(A)
        if op == "qr" and m < n:
            # gels_using_factor covers only the overdetermined case; the
            # underdetermined minimum-norm path needs LQ factors (gels
            # handles it per call). Reject at registration instead of
            # crashing on the first solve.
            raise SlateError(
                "Session.register: wide (m < n) operators are not "
                "servable via resident QR; use least_squares_solve "
                "per call")
        if op in SPECTRAL_OPS:
            # round 19: resident spectral operators (spectral/) — the
            # staged two-stage decomposition needs a dense TiledMatrix
            # (eig additionally a Hermitian/Symmetric one); wide SVD
            # operands register the transpose (api.svd handles wide
            # per call)
            if not isinstance(A, TiledMatrix):
                raise SlateError(
                    f"Session.register: op {op!r} requires a "
                    f"TiledMatrix operand, got {type(A).__name__}")
            if op == "eig":
                if A.kind not in (MatrixKind.Hermitian,
                                  MatrixKind.Symmetric) or m != n:
                    raise SlateError(
                        "Session.register: op 'eig' requires a square "
                        "Hermitian/Symmetric TiledMatrix operand")
            elif m < n:
                raise SlateError(
                    "Session.register: wide (m < n) operators are not "
                    "servable via resident SVD; register the "
                    "transpose (api.svd handles wide per call)")
        policy = None
        if refine is not None and refine is not False:
            if op not in ("lu", "chol", "lu_small", "chol_small"):
                raise SlateError(
                    f"Session.register: refine covers lu/chol operators "
                    f"(dense or small), not {op!r}")
            wd = A.dtype
            if refine is True:
                # table resolution keys off the dense op family — a
                # small operator follows the same (op, n, dtype) rules.
                # A MATCHED rule whose policy is None is an explicit
                # full-precision carve-out (PolicyTable.add(None, ...)):
                # the operator registers unrefined. Only a class no
                # rule covers falls to the dtype ladder — and only
                # ladder exhaustion (c64) is the error.
                from ..refine.policy import default_factor_dtype
                matched, policy = self.refine_policies.lookup(
                    op.replace("_small", ""), n, wd)
                if not matched:
                    lo = default_factor_dtype(wd)
                    if lo is None:
                        raise SlateError(
                            f"Session.register: no refine policy "
                            f"resolves for (op={op!r}, n={n}, "
                            f"dtype={wd}) — no lower factor precision "
                            "exists on the dtype ladder")
                    policy = RefinePolicy(factor_dtype=lo)
            else:
                policy = refine
            if policy is not None:
                try:
                    policy.validate_for(wd)
                except ValueError as e:
                    raise SlateError(f"Session.register: {e}")
                if policy.strategy == "gmres" and (op in SMALL_OPS
                                                   or grid is not None):
                    raise SlateError(
                        "Session.register: GMRES-IR serving covers "
                        "single-device dense operators; use "
                        "strategy='ir' for mesh or small-problem "
                        "operators")
        eopts = opts or self.opts
        tuned = None
        if self.tuning is not None:
            # round 21: first-match (op, n-bucket, dtype, platform)
            # resolution — matched knobs land in THIS operator's opts
            # (nb -> block_size, inner_blocking, lookahead) before any
            # program is built, so warmup compiles the tuned program
            # and the serve path after warmup is zero new compiles;
            # unmatched operators keep their defaults (the documented
            # fallback). One `tuning is None` check when disabled.
            dt = A.ab.dtype if isinstance(A, PackedBand) else A.dtype
            cfg = self.tuning.resolve(op, n, str(np.dtype(dt)),
                                      jax.default_backend())
            if cfg is not None:
                eopts = cfg.apply(eopts)
                tuned = cfg.label()
        with self._lock:
            if handle is None:
                self._seq += 1
                while self._seq in self._ops:  # skip caller-chosen ints
                    self._seq += 1
                handle = self._seq
            if handle in self._ops:
                raise SlateError(f"Session.register: handle {handle!r} "
                                 "already registered (unregister first)")
            self._ops[handle] = _Operator(
                A, op, eopts, m, n, band, grid=grid,
                refine=policy,
                tenant=None if tenant is None else str(tenant),
                tuned=tuned)
        return handle

    def _resolve_tuned(self, entry: _Operator):
        """The table's TunedConfig for one registered operator (None
        without a table or match) — the shadow tuner's first ladder
        rung and the register-time resolution, one vocabulary."""
        if self.tuning is None:
            return None
        A = entry.A
        dt = A.ab.dtype if isinstance(A, PackedBand) else A.dtype
        return self.tuning.resolve(entry.op, entry.n, str(np.dtype(dt)),
                                   jax.default_backend())

    def tuned_width_quantum(self, handle: Hashable) -> int:
        """The Batcher's rhs-width pad quantum for ``handle`` (round
        21): the table's ``width_quantum`` when one matches, else 1 —
        plain pow2 padding, bit-identical to the untuned tree."""
        if self.tuning is None:
            return 1
        with self._lock:
            entry = self._ops.get(handle)
        if entry is None:
            return 1
        A = entry.A
        dt = A.ab.dtype if isinstance(A, PackedBand) else A.dtype
        return self.tuning.width_quantum(entry.op, entry.n,
                                         str(np.dtype(dt)),
                                         jax.default_backend())

    @staticmethod
    def _infer_op(A) -> str:
        if isinstance(A, PackedBand):
            return "band_chol" if A.hermitian else "band_lu"
        if not hasattr(A, "kind"):
            # plain dense [n, n] array: the small-problem engine (a
            # symmetry-blind default — register op="chol_small"
            # explicitly for Hermitian-positive-definite operators)
            return "lu_small"
        if A.kind in (MatrixKind.Hermitian, MatrixKind.Symmetric,
                      MatrixKind.HermitianBand):
            return "chol"
        if A.shape[0] != A.shape[1]:
            return "qr"
        return "lu"

    def unregister(self, handle: Hashable):
        """Drop an operator and its cached factor (no error if absent)."""
        with self._lock:
            entry = self._ops.pop(handle, None)
            res = self._cache.pop(handle, None)
            if res is not None:
                self.metrics.inc("evictions")
                self.metrics.inc("evicted_bytes", res.nbytes)
                if self.attribution is not None:
                    self._attr_evicted(handle)
                rec = self.recorder
                if rec is not None:
                    self._journal_evict(rec, handle, res.nbytes,
                                        "unregister", entry=entry)
            if self.attribution is not None:
                # the handle can never be accessed again: drop its
                # heat/residency clocks (and gauge) so handle churn
                # cannot leak ledger state — the cells stay (billing
                # history)
                self.attribution.forget_handle(handle)
            if self.numerics is not None:
                # same churn-cardinality discipline for the health row
                # and its handle_health gauge
                self.numerics.forget(handle)
            self._update_hbm_gauges()

    def __contains__(self, handle: Hashable) -> bool:
        with self._lock:
            return handle in self._ops

    def handles(self):
        with self._lock:
            return list(self._ops)

    # -- cache -------------------------------------------------------------

    @property
    def cached_bytes(self) -> int:
        with self._lock:
            return sum(r.nbytes for r in self._cache.values())

    def cached_handles(self):
        """LRU → MRU order."""
        with self._lock:
            return list(self._cache)

    def evict(self, handle: Hashable) -> bool:
        """Explicitly drop a cached factor (operator stays registered)."""
        with self._lock:
            res = self._cache.pop(handle, None)
            if res is not None:
                self.metrics.inc("evictions")
                self.metrics.inc("evicted_bytes", res.nbytes)
                if self.attribution is not None:
                    self._attr_evicted(handle)
                rec = self.recorder
                if rec is not None:
                    self._journal_evict(rec, handle, res.nbytes,
                                        "explicit")
            self._update_hbm_gauges()
        return res is not None

    def clear_cache(self):
        with self._lock:
            n = len(self._cache)
            nbytes = sum(r.nbytes for r in self._cache.values())
            if self.attribution is not None:
                for h in self._cache:
                    self._attr_evicted(h)
            self._cache.clear()
            self._update_hbm_gauges()
        self.metrics.inc("evictions", n)
        self.metrics.inc("evicted_bytes", nbytes)
        rec = self.recorder
        if rec is not None and n:
            # one sweep, one decision: count carries the victim total
            # so journal-count parity vs the ``evictions`` counter holds
            rec.decision("eviction", outcome="clear_cache", count=n,
                         inputs={"nbytes": nbytes})

    def factor(self, handle: Hashable) -> _Resident:
        """Resident factor for ``handle``: cache hit or refactor-on-miss
        (LRU-touch either way, evict-to-budget on insert)."""
        with self._lock:
            entry = self._ops.get(handle)
            if entry is None:
                raise SlateError(f"Session: unknown handle {handle!r}")
            attr = self.attribution
            res = self._cache.get(handle)
            if res is not None:
                self._cache.move_to_end(handle)
                self.metrics.inc("cache_hits")
                if attr is not None:
                    # hit: count + heat advance, and re-touch the
                    # residency clock (accrued byte-seconds credit the
                    # cell and the global counter with the same value)
                    attr.access(entry.tenant, handle, True)
                    inc = attr.touch_residency(entry.tenant, handle,
                                               res.nbytes)
                    if inc:
                        self.metrics.inc("residency_byte_seconds_total",
                                         inc)
                if self.slo is not None:
                    self.slo.record_cache(True)
                return res
            self.metrics.inc("cache_misses")
            if attr is not None:
                attr.access(entry.tenant, handle, False)
            if self.slo is not None:
                self.slo.record_cache(False)
            # attrs built only when tracing is on: the disabled path
            # must not allocate per solve (ISSUE 4 acceptance)
            fattrs = (self._span_attrs(entry, handle)
                      if self.tracer.enabled else {})
            with self.metrics.phase("serve.factor", "factor_latency",
                                    tracer=self.tracer, **fattrs):
                res = self._factor(entry, handle)
                if (self.faults is not None and entry.refine is not None
                        and res.info == 0
                        and self._fault("refine.lo_factor")):
                    # injected singular low-precision operand: the lo
                    # factor "fails", driving the SAME counted
                    # working-precision fallback a real indefinite-
                    # under-rounding operand takes
                    res = _Resident(res.payload, 1, res.nbytes,
                                    res.nbytes_total)
                if res.info != 0 and entry.refine is not None:
                    # the LOW-precision factorization itself failed
                    # (e.g. SPD in f32, indefinite after bf16
                    # rounding): a counted refinement fallback — the
                    # working-precision refactor is the answer path,
                    # never the garbage factor
                    self.metrics.inc("refine_fallbacks_total")
                    _obs_log.warning(
                        "refine fallback: low-precision (%s) factor of "
                        "%r failed (info=%d); refactoring at working "
                        "precision", entry.refine.factor_dtype, handle,
                        res.info)
                    rec = self.recorder
                    if rec is not None:
                        rec.decision(
                            "refine_fallback", op=entry.op,
                            handle=handle, tenant=entry.tenant,
                            outcome="lo_factor_failed",
                            inputs={
                                "info": int(res.info),
                                "factor_dtype":
                                    str(entry.refine.factor_dtype)})
                    if not entry.refine.fallback:
                        raise SlateError(
                            f"Session: low-precision factor of "
                            f"{handle!r} failed (info={res.info}) and "
                            "the refine policy disables fallback")
                    entry.refine = None
                    res = self._factor(entry, handle)
            self.metrics.inc("factors_total")
            fl = _factor_flops(entry.op, entry.m, entry.n, entry.band)
            self.metrics.inc("flops_total", fl)
            self.metrics.inc("factor_flops_total", fl)
            # executed work credits the PROCESS ledger here (the api.*
            # verbs inside the compiled factor program only run at
            # trace time and deliberately credit nothing — obs.driver).
            # Band factors are the exception: _factor runs them through
            # the EAGER api verbs, whose driver hook already credited
            # the ledger — crediting serve.factor too would double-count
            if entry.op not in ("band_lu", "band_chol"):
                _LEDGER.record("serve.factor", fl)
            if attr is not None:
                # the factor work belongs to the operator's tenant;
                # same grid-snapped value as the counters above
                attr.record("factor_flops", entry.tenant, handle, fl)
            self._cache[handle] = res
            # a fresh factor zeroes the incremental-update error
            # accrual (round 20; the numerics monitor resets its own
            # copy in record_factor — this is the monitor-less one)
            entry.updates = 0
            entry.update_weight = 0.0
            if attr is not None:
                # open the residency interval: byte-seconds accrue
                # from this insert until eviction/unregister. A
                # factor-on-miss implies no interval is open (inc=0),
                # but crediting the return keeps the seam conserving
                # by construction like every other residency seam
                inc = attr.touch_residency(entry.tenant, handle,
                                           res.nbytes)
                if inc:
                    self.metrics.inc("residency_byte_seconds_total",
                                     inc)
            self._evict_to_budget(keep=handle)
            if self.tenant_policies is not None:
                # round 18: the tenant's own sub-budget, after the
                # global pass (per-tenant LRU, isolation pinned)
                self._evict_tenant_to_budget(entry.tenant, keep=handle)
            if self.numerics is not None and res.info == 0:
                res = self._numerics_after_factor(entry, handle, res)
            return res

    def _numerics_after_factor(self, entry: _Operator, handle: Hashable,
                               res: _Resident) -> _Resident:
        """Caller holds the lock and verified ``self.numerics``.
        Factor-time health signals on a fresh resident: the realized
        growth bound (host read of the factor; skipped for mesh
        residents — their factor-time signal is the condest, which
        runs sharded) with its NaN/Inf sentinel, then the condest
        probe (config-gated). Returns the SERVING resident: a reflex
        demotion mid-signal evicts the lo factor, so this refactors at
        working precision before returning (bounded recursion — the
        demoted entry has ``refine=None`` and cannot demote again)."""
        nm = self.numerics
        cfg = nm.config
        growth = None
        finite = True
        if (cfg.growth_on_factor and entry.grid is None
                and entry.op in CONDEST_OPS):
            growth = (_num.chol_growth if "chol" in entry.op
                      else _num.lu_growth)(res.payload[0], entry.A)
            if not np.isfinite(growth):
                finite = False
                self.metrics.inc("numerics_nonfinite_total")
        old, new = nm.record_factor(
            handle, entry.op, _work_dtype_name(entry),
            factor_dtype=(None if entry.refine is None
                          else entry.refine.factor_dtype),
            tenant=entry.tenant, growth=growth, finite=finite)
        self._health_reflex(entry, handle, old, new)
        if (cfg.condest_on_factor and entry.op in CONDEST_OPS
                and handle in self._cache):
            self._condest_locked(entry, handle, res)
        if handle not in self._cache:
            # a reflex demoted this handle off the refine ladder and
            # evicted its lo resident: serve from a working-precision
            # refactor, never from the factor the reflex just rejected
            return self.factor(handle)
        return res

    def factor_info(self, handle: Hashable) -> int:
        """info of the resident factor (factoring on miss). A cached
        factor is peeked without counting a hit or touching LRU order,
        so an info-check-then-solve pair costs one cache access."""
        with self._lock:
            res = self._cache.get(handle)
            if res is not None:
                return res.info
            return self.factor(handle).info

    def _factor(self, entry: _Operator, handle: Hashable = None
                ) -> _Resident:
        op, A, opts = entry.op, entry.A, entry.opts
        if op in SPECTRAL_OPS:
            payload = self._factor_spectral(entry, handle)
            payload = jax.block_until_ready(payload)
            # the two-stage pipeline finishes through stedc's D&C,
            # which is direct (no convergence failure mode to report):
            # a spectral resident is always info=0
            return _Resident(payload, 0,
                             _tree_nbytes(payload, per_chip=True),
                             _tree_nbytes(payload))
        if op in SMALL_OPS:
            # the per-request arm of the many-small-problems engine:
            # ONE item through the SAME hand-batched kernels the
            # grouped dispatch uses at B=bucket (linalg/batched's
            # per-bucket program cache compiles/reuses the B=1
            # program) — so a cached factor is bit-identical to the
            # slice a batched factor would have produced
            from ..linalg import batched as _batched
            if entry.refine is not None:
                # the mixed arm: cast+factor in the policy's dtype
                # through the SAME bucket programs the grouped mixed
                # dispatch runs at B=bucket — a cached lo factor is
                # bit-identical to the slice a batched mixed factor
                # would have produced (and charges factor-dtype bytes)
                lo = entry.refine.factor_dtype
                if op == "lu_small":
                    lu, perm, info = _batched.getrf_mixed_batched(
                        A[None], lo)
                    payload = (lu[0], perm[0])
                else:
                    l, info = _batched.potrf_mixed_batched(A[None], lo)
                    payload = (l[0],)
            elif op == "lu_small":
                lu, perm, info = _batched.getrf_batched(A[None])
                payload = (lu[0], perm[0])
            else:
                l, info = _batched.potrf_batched(A[None])
                payload = (l[0],)
            payload = jax.block_until_ready(payload)
            return _Resident(payload, int(info[0]),
                             _tree_nbytes(payload))
        if op in ("band_lu", "band_chol"):
            # band factors stay on the eager verbs (PackedBand pipelines
            # host-side packing the whole-program jit cannot absorb)
            if op == "band_lu":
                LU, perm, info = api.lu_factor(A, opts)
                payload = (LU, perm)
            else:
                L, info = api.chol_factor(A, opts)
                payload = (L,)
        else:
            # dense factors run as ONE compiled program (round 7):
            # warmup() AOT-compiles it per operand shape, so a served
            # operator's first refactor-on-miss skips tracing AND
            # compilation — and the program is the LOOKAHEAD pipeline
            # (entry.opts.lookahead flows into the jitted driver), so
            # served factors compile the lookahead variant ahead of the
            # first request (ISSUE 3 satellite).
            key = self._factor_key(entry)
            exe = self._compiled.get(key)
            if exe is None and (entry.grid is not None
                                or entry.refine is not None):
                # mesh discipline: the factor ALWAYS runs as one
                # analyzed sharded AOT program per shape — the census
                # and per-chip transient accounting need the compiled
                # seam, and warmup() may not have covered this shape
                # (this is the on-request-path compile, counted).
                # Round 13 extends the discipline to REFINED entries:
                # the low-precision factor program is analyzed so its
                # bytes/census credit per execution (ISSUE 10 —
                # "through the AOT seam as analyzed programs")
                exe = self._aot_compile("factor", entry, handle,
                                        self._factor_fn(entry), (A,),
                                        key=key)
                self._compiled_put(key, exe)
                self.metrics.inc("factor_aot_compiles")
            if exe is not None:
                self._compiled.move_to_end(key)
                payload, info = exe(A)
                self._credit_program(key, "serve.factor",
                                     tenant=entry.tenant, handle=handle)
            else:
                payload, info = self._factor_fn(entry)(A)
        payload = jax.block_until_ready(payload)
        return _Resident(payload, int(info),
                         _tree_nbytes(payload, per_chip=True),
                         _tree_nbytes(payload))

    def _factor_spectral(self, entry: _Operator, handle: Hashable):
        """Caller holds the lock. The round-19 spectral factorization:
        run the staged two-stage pipeline (spectral/mesh.py) with every
        DEVICE stage routed through the ``_aot_compile`` seam — each
        stage is a cost-analyzed program whose bytes/collective census
        credit per execution (the mesh-factor discipline of round 11,
        applied per stage because the host stedc round-trip splits the
        pipeline). Returns the resident pytree payload
        (EigFactors/SVDFactors) with the spectrum replicated over the
        operator's grid."""
        from .. import spectral as _spectral

        def stage(name, jfn, args):
            leaves, treedef = jax.tree_util.tree_flatten(args)
            shapes = tuple((tuple(l.shape), str(l.dtype))
                           for l in leaves)
            key = ("spectral", name, entry.op, entry.opts, treedef,
                   shapes)
            exe = self._compiled.get(key)
            if exe is None:
                exe = self._aot_compile(name, entry, handle, jfn, args,
                                        key=key)
                self._compiled_put(key, exe)
                self.metrics.inc("factor_aot_compiles")
            else:
                self._compiled.move_to_end(key)
            self._credit_program(key, "serve.factor",
                                 tenant=entry.tenant, handle=handle)
            return exe(*args)

        if entry.op == "eig":
            lam, V = _spectral.heev_staged(entry.A, entry.opts,
                                           stage=stage)
            if entry.grid is not None:
                lam = jax.device_put(lam, entry.grid.replicated())
            return _spectral.EigFactors(V, lam)
        s, U, V = _spectral.svd_staged(entry.A, entry.opts, stage=stage)
        if entry.grid is not None:
            s = jax.device_put(s, entry.grid.replicated())
        return _spectral.SVDFactors(U, s, V)

    def _credit_program(self, key: Hashable, op: str,
                        waste_fraction: float = 0.0,
                        tenant: Optional[str] = None,
                        handle: Optional[Hashable] = None):
        """One execution of an analyzed AOT program: credit the process
        BYTES ledger (bytes-accessed + modeled collective traffic) and
        the session counters — the per-execution discipline the flop
        ledger already follows (compile-time tracing credits nothing).

        ``waste_fraction`` (round 12) is the padded share of the
        program's columns (the Batcher's pow2 width quantization): that
        share of the bytes/ICI traffic moves to the ``padding.waste``
        ledger op and the ``padding_waste_bytes`` counter instead of
        ``op`` — executed totals preserved, useful-work attribution
        honest. The per-kind collective census stays whole under the
        useful record (instruction counts are structural, not
        column-divisible)."""
        pc = self._program_costs.get(key)
        if pc is None:
            return
        if waste_fraction > 0.0:
            wf = min(max(waste_fraction, 0.0), 1.0)
            ba = pc.bytes_accessed or 0.0
            _costs.BYTES.record(op, ba * (1.0 - wf),
                                pc.collective_bytes * (1.0 - wf),
                                pc.collectives)
            _costs.BYTES.record("padding.waste", ba * wf,
                                pc.collective_bytes * wf)
            if ba:
                self.metrics.inc("padding_waste_bytes", ba * wf)
        else:
            _costs.BYTES.record_costs(op, pc)
        # the session counters (and round-15 attribution cells) take
        # the GRID-SNAPPED program bytes — XLA byte counts are whole
        # numbers anyway, and the snap is what makes the per-tenant
        # conservation sums exact (obs/attribution.py); the process
        # BYTES ledger above keeps the raw analysis values
        attr = self.attribution
        if pc.bytes_accessed:
            ba = _fl_grid(pc.bytes_accessed)
            self.metrics.inc("bytes_accessed_total", ba)
            if attr is not None and handle is not None:
                attr.record("bytes", tenant, handle, ba)
        if pc.collective_bytes:
            cb = _fl_grid(pc.collective_bytes)
            self.metrics.inc("collective_bytes_total", cb)
            if attr is not None and handle is not None:
                attr.record("ici_bytes", tenant, handle, cb)
            # per-verb ICI split (round 11): a capacity planner needs
            # the steady-state (solve) traffic separate from the
            # amortized factor traffic — both move per EXECUTION
            self.metrics.inc(
                ("solve_collective_bytes_total" if op == "serve.solve"
                 else "factor_collective_bytes_total"),
                cb)

    def _jit_cached(self, jkey: Hashable, make):
        """LRU-jit-cache shared by the solve and factor programs. A
        miss means the next call pays tracing (+compilation unless an
        AOT executable covers the shape) on the request path — counted
        so a serving fleet can alarm on jit-cache churn."""
        fn = self._jit.get(jkey)
        if fn is None:
            self.metrics.inc("jit_cache_misses")
            fn = self._jit[jkey] = jax.jit(make())
            while len(self._jit) > self._jit_cap:
                self._jit.popitem(last=False)
        else:
            self._jit.move_to_end(jkey)
        return fn

    def _compiled_put(self, key: Hashable, exe):
        """Insert an AOT executable under the shared cap (its cost
        analysis is dropped in step, so the transient-footprint term of
        the budget only counts programs that can still run)."""
        self._compiled[key] = exe
        while len(self._compiled) > self._compiled_cap:
            old, _ = self._compiled.popitem(last=False)
            self._program_costs.pop(old, None)

    def _factor_fn(self, entry: _Operator):
        if entry.refine is not None:
            # the refine engine's cast+factor program (the policy is
            # part of the key: two operators refined under different
            # factor dtypes never share a closure)
            return self._jit_cached(
                ("factor", entry.op, entry.opts, entry.refine),
                lambda: _refine_engine.make_factor_fn(
                    entry.op, entry.opts, entry.refine))
        return self._jit_cached(
            ("factor", entry.op, entry.opts),
            lambda: _make_factor_fn(entry.op, entry.opts))

    @staticmethod
    def _factor_key(entry: _Operator) -> Hashable:
        leaves, treedef = jax.tree_util.tree_flatten(entry.A)
        shapes = tuple((tuple(l.shape), str(l.dtype)) for l in leaves)
        return ("factor", entry.op, entry.opts, entry.refine, treedef,
                shapes)

    def _largest_transient(self) -> int:
        """Caller holds the lock. Transient HBM (temp scratch + output
        allocation) of the largest resident AOT program — the
        peak-memory truth XLA's memory_analysis reports at the compile
        seam. 0 when no program has been analyzed (XLA:CPU reports 0
        temp bytes: graceful degradation to the round-6 accounting)."""
        return max((pc.transient_bytes
                    for pc in self._program_costs.values()), default=0)

    def _update_hbm_gauges(self):
        """Caller holds the lock. Publish the HBM truth as gauges:
        resident factor bytes (the PER-CHIP charge — max-per-shard for
        mesh residents, the whole factor on a single device), the
        worst-case per-chip peak (factors + largest program transient —
        XLA's memory analysis describes the per-device SPMD module),
        the aggregate bytes across the mesh, and the per-chip headroom
        against the budget."""
        resident = sum(r.nbytes for r in self._cache.values())
        peak = resident + self._largest_transient()
        self.metrics.set_gauge("resident_bytes", resident)
        self.metrics.set_gauge(
            "resident_bytes_total",
            sum(r.nbytes_total for r in self._cache.values()))
        self.metrics.set_gauge("peak_hbm_bytes", peak)
        if self.hbm_budget is not None:
            self.metrics.set_gauge("hbm_headroom", self.hbm_budget - peak)

    def hbm_headroom(self) -> Optional[int]:
        """PER-CHIP budget minus (per-chip resident factor charge +
        largest program's per-device transient); None when the session
        is unbounded."""
        with self._lock:
            if self.hbm_budget is None:
                return None
            return self.hbm_budget - (
                sum(r.nbytes for r in self._cache.values())
                + self._largest_transient())

    def _eviction_order(self):
        """Caller holds the lock. The LRU walk order, except SUSPECT
        handles lose eviction tie-breaks (round 16): a resident the
        numerics monitor distrusts is the cheapest thing to give back
        — its next touch refactors anyway if the operand really
        degraded, and keeping it pins HBM a healthy handle could use.
        LRU order is preserved within each health class; with numerics
        disabled this is exactly ``list(self._cache)`` (one None
        check)."""
        keys = list(self._cache)
        nm = self.numerics
        if nm is None:
            return keys
        sus = [h for h in keys if nm.health(h) == "suspect"]
        if not sus:
            return keys
        smark = set(sus)
        return sus + [h for h in keys if h not in smark]

    def _evict_to_budget(self, keep: Hashable):
        """Caller holds the lock. Drop LRU entries (never ``keep``)
        until resident factors PLUS the largest resident program's
        transient footprint fit the budget (round 9: the budget used to
        be an honor-system sum of factor nbytes that ignored what the
        programs themselves allocate while running)."""
        budget = self.hbm_budget
        if self.faults is not None and self._fault("hbm"):
            # injected HBM exhaustion: for THIS insert the budget
            # collapses to zero — eviction-under-pressure runs for
            # real (everything but `keep` drops; `keep` then counts a
            # budget overflow exactly like a genuinely over-budget
            # factor). An unbounded session degrades the same way.
            budget = 0
        if budget is None:
            self._update_hbm_gauges()
            return
        transient = self._largest_transient()
        used = sum(r.nbytes for r in self._cache.values()) + transient
        for h in self._eviction_order():
            if used <= budget:
                break
            if h == keep:
                continue
            nbytes = self._cache.pop(h).nbytes
            used -= nbytes
            self.metrics.inc("evictions")
            self.metrics.inc("evicted_bytes", nbytes)
            if self.attribution is not None:
                self._attr_evicted(h)
            rec = self.recorder
            if rec is not None:
                self._journal_evict(rec, h, nbytes, "budget",
                                    used=used, budget=budget)
        if used > budget:
            # the kept factor (+ program transient) alone exceeds the
            # budget; serving must continue, but this is OOM risk —
            # record the overflow and warn on the slow-log path
            self.metrics.inc("budget_overflows")
            self.metrics.inc("oom_risk_warnings")
            _obs_log.warning(
                "OOM risk: resident factors + largest program transient "
                "= %d bytes exceed hbm_budget=%d (transient=%d); serving "
                "continues with negative headroom", used, budget,
                transient)
        if self.slo is not None:
            # one budget check = one oom_risk SLO event (good = fits;
            # an injected exhaustion records the bad event it simulates)
            self.slo.record_oom(used <= budget)
        self._update_hbm_gauges()

    # -- per-tenant HBM sub-budgets (round 18, runtime/tenancy.py) ---------

    @staticmethod
    def _tname(tenant) -> str:
        return DEFAULT_TENANT if tenant is None else str(tenant)

    def tenant_resident_bytes(self, tenant=None) -> int:
        """Per-chip resident factor bytes charged to one tenant (the
        sub-budget's numerator). Lock-free (GIL-atomic dict walks over
        immutable fields — the op_meta discipline): scrapes and the
        fleet's migration-source scan must not wait on an in-flight
        solve."""
        t = self._tname(tenant)
        total = 0
        for h, res in list(self._cache.items()):
            e = self._ops.get(h)
            if e is not None and self._tname(e.tenant) == t:
                total += res.nbytes
        return total

    def _evict_tenant_to_budget(self, tenant, keep: Hashable):
        """Caller holds the lock and verified ``self.tenant_policies``.
        The per-tenant HBM sub-budget, enforced at the factor-insert
        seam: when THIS tenant's resident bytes exceed its declared
        ``max_resident_bytes``, evict ITS residents in LRU order
        (never ``keep``, never another tenant's — the isolation pin:
        tenant A's pressure cannot evict tenant B's resident; the
        GLOBAL budget in _evict_to_budget remains the only
        cross-tenant eviction authority). A kept factor alone over the
        sub-budget counts ``tenant_quota_overflows`` — serving
        continues, the tenant is over its declared share, and the
        gauge pair says so."""
        t = self._tname(tenant)
        pol = self.tenant_policies.policy(t)
        sub = None if pol is None else pol.max_resident_bytes
        used = 0
        for h, res in self._cache.items():
            e = self._ops.get(h)
            if e is not None and self._tname(e.tenant) == t:
                used += res.nbytes
        if sub is not None:
            # the SAME walk order the global budget uses
            # (_eviction_order: round-16 suspect residents lose
            # tie-breaks, then LRU), filtered to this tenant — one
            # eviction policy, two budget scopes
            mine = [h for h in self._eviction_order()
                    if (e := self._ops.get(h)) is not None
                    and self._tname(e.tenant) == t]
            for h in mine:
                if used <= sub:
                    break
                if h == keep:
                    continue
                nbytes = self._cache.pop(h).nbytes
                used -= nbytes
                self.metrics.inc("evictions")
                self.metrics.inc("evicted_bytes", nbytes)
                self.metrics.inc("tenant_quota_evictions_total")
                if self.attribution is not None:
                    self._attr_evicted(h)
                # ONE decision, TWO counters (evictions + the tenant
                # quota secondary): outcome "tenant_quota" carries the
                # OUTCOME_COUNTERS parity for the second one
                rec = self.recorder
                if rec is not None:
                    self._journal_evict(rec, h, nbytes, "tenant_quota",
                                        used=used, sub_budget=sub)
            if used > sub:
                self.metrics.inc("tenant_quota_overflows")
                _obs_log.warning(
                    "tenant quota: %r resident bytes %d exceed the "
                    "declared sub-budget %d (the kept factor alone is "
                    "over it); serving continues over-share", t, used,
                    sub)
            self._update_hbm_gauges()
        self.metrics.set_gauge(f"tenant_quota_resident_bytes:{t}", used)
        if sub is not None:
            self.metrics.set_gauge(f"tenant_quota_hbm_headroom:{t}",
                                   sub - used)

    def quotas_payload(self) -> dict:
        """The quota view of the ``/tenants`` route (round 18): the
        declared policy table, each tenant's live resident bytes
        against its sub-budget, and the quota counters.
        ``{"enabled": false}`` without a table."""
        if self.tenant_policies is None:
            return {"enabled": False, "tenants": {}}
        per: Dict[str, dict] = {}
        for h, res in list(self._cache.items()):
            e = self._ops.get(h)
            if e is None:
                continue
            t = self._tname(e.tenant)
            row = per.setdefault(t, {"resident_bytes": 0,
                                     "residents": 0})
            row["resident_bytes"] += res.nbytes
            row["residents"] += 1
        for t in list(per):
            pol = self.tenant_policies.policy(t)
            per[t]["max_resident_bytes"] = (
                None if pol is None else pol.max_resident_bytes)
            per[t]["weight"] = self.tenant_policies.weight(t)
        return {
            "enabled": True,
            "policies": self.tenant_policies.to_dict(),
            "tenants": per,
            "counters": {k: self.metrics.get(k) for k in (
                "quota_rejections_total",
                "tenant_quota_evictions_total",
                "tenant_quota_overflows", "tenant_sheds_total")},
        }

    # -- solve -------------------------------------------------------------

    def _span_attrs(self, entry: _Operator, handle: Hashable) -> dict:
        """Span attributes for one operator: op, shape, dtype, nb,
        lookahead, handle — the vocabulary the ISSUE fixes."""
        A = entry.A
        dtype = A.ab.dtype if isinstance(A, PackedBand) else A.dtype
        attrs = {
            "op": entry.op, "m": entry.m, "n": entry.n,
            "nb": getattr(A, "nb", entry.band),
            "dtype": str(dtype),
            "lookahead": getattr(entry.opts, "lookahead", 0),
            "handle": repr(handle),
        }
        if entry.grid is not None:
            attrs["mesh"] = f"{entry.grid.p}x{entry.grid.q}"
        if entry.refine is not None:
            attrs["factor_dtype"] = entry.refine.factor_dtype
            attrs["refine_strategy"] = entry.refine.strategy
        if entry.tuned is not None:
            # round 21: which tuning-table row (or shadow promotion)
            # configured this operator — attribution joins it per
            # tenant, making tables workload-aware
            attrs["tuned_config"] = entry.tuned
        return attrs

    def solve_matrix(self, handle: Hashable, B: TiledMatrix,
                     served_cols: Optional[int] = None,
                     tenant: Optional[str] = None,
                     spectral_fn: str = "solve",
                     theta: float = 0.0) -> TiledMatrix:
        """Solve with the resident factor; B is a TiledMatrix (dense
        ops) or a padded dense array (band ops). Returns the TiledMatrix
        (or array) solution. Raises on factorization failure (info>0).

        ``served_cols``: how many of B's columns are real client
        requests (default: all). The Batcher's pow2 width padding
        passes the pre-padding count so ``solves_total`` keeps meaning
        "client columns served" — the denominator of every per-solve
        rate — while the flop/bytes ledgers keep crediting the
        EXECUTED width (padding waste is real device work a fleet
        should see)."""
        with self._lock:
            entry = self._ops[handle] if handle in self._ops else None
            if entry is None:
                raise SlateError(f"Session: unknown handle {handle!r}")
            if entry.op in SMALL_OPS:
                raise SlateError(
                    "Session.solve_matrix: small-problem operators take "
                    "plain arrays — use Session.solve")
            # the request's tenant (round 15): explicit override ->
            # operator tenant -> default; resolved only when someone
            # consumes it (the attr/slo disabled path allocates nothing)
            attr = self.attribution
            rt = (self.request_tenant(handle, tenant)
                  if (attr is not None or self.slo is not None) else None)
            hit = handle in self._cache  # before factor() counts it
            res = self.factor(handle)
            if res.info != 0:
                if self.slo is not None:
                    self.slo.record_request(entry.op, entry.n, 0.0,
                                            ok=False, source="solve",
                                            tenant=rt)
                raise SlateError(
                    f"Session: operator {handle!r} factorization failed "
                    f"(info={res.info})")
            # sampled residual probe (round 16): the deterministic
            # sampler decides BEFORE dispatch whether this solve runs
            # the fused solve+residual program instead of the plain
            # one — one extra gemm in-program, one host sync, zero
            # extra programs for unprobed solves. Refined entries skip
            # it (their per-iteration residuals already feed the
            # refine-drift signal). AFTER the info raise on purpose: a
            # failed solve never consumes a decision, on any path —
            # the probe schedule stays a pure function of the
            # SUCCESSFUL request stream (grouped-parity pin).
            nm = self.numerics
            probe = (nm is not None and entry.refine is None
                     and entry.op in PROBE_OPS + SPECTRAL_OPS
                     and nm.sampler.decide())
            k = int(B.shape[1])
            served = k if served_cols is None else int(served_cols)
            tr = self.tracer
            sattrs = (dict(self._span_attrs(entry, handle), k=k,
                           cache_hit=hit) if tr.enabled else {})
            if self.faults is not None:  # the whole disabled-path cost
                self._fault("dispatch")
            with self.metrics.phase("serve.solve", "solve_latency",
                                    tracer=tr, **sattrs) as ph:
                # dispatch (trace/launch) and device-block are split
                # sub-spans so a trace shows where the latency sits —
                # and stage histograms (round 12), so the split is
                # visible in /metrics even with tracing off
                t0 = time.perf_counter()
                pstats = None
                with tr.span("serve.dispatch"):
                    if entry.op in SPECTRAL_OPS:
                        X = self._dispatch_spectral(
                            entry, res, B, handle, spectral_fn, theta,
                            served_cols=served_cols, tenant=rt)
                        if probe:
                            # the spectral residual probe is a SEPARATE
                            # one-gemm program (‖A·v_i − λ_i·v_i‖ on
                            # sampled columns — it reads the resident,
                            # not the request), run alongside the apply
                            pstats = self._spectral_probe(entry, res,
                                                          B, handle)
                    elif probe:
                        X, pstats = self._dispatch_probed(
                            entry, res, B, handle,
                            served_cols=served_cols, tenant=rt)
                    else:
                        X = self._dispatch(entry, res, B, handle,
                                           served_cols=served_cols,
                                           tenant=rt)
                t1 = time.perf_counter()
                with tr.span("serve.block"):
                    X = jax.block_until_ready(X)
                    if pstats is not None:
                        # same program, already executed with X — the
                        # fetch rides the one existing host sync
                        pstats = np.asarray(
                            jax.block_until_ready(pstats))
                t2 = time.perf_counter()
            ex = getattr(ph.span, "trace_id", None)  # exemplar join key
            self.metrics.observe("stage_dispatch", t1 - t0, exemplar=ex)
            self.metrics.observe("stage_device_execute", t2 - t1,
                                 exemplar=ex)
            if attr is not None:
                # device-execute seconds on the dyadic grid — the same
                # snapped value lands in the cell and the global
                ds = _s_grid(t2 - t1)
                self.metrics.inc("device_seconds_total", ds)
                attr.record("device_seconds", rt, handle, ds)
            self.metrics.inc("solves_total", served)
            self.metrics.inc("dispatches_total")
            # padding-waste split (round 12): the Batcher's pow2 width
            # quantization executes k - served REAL zero columns —
            # device work the fleet must see, but not useful work. The
            # solve models are k-linear, so the split is exact:
            # useful + waste = the executed total the old code credited.
            fl = _solve_flops(entry.op, entry.m, entry.n, served,
                              entry.band)
            waste_fl = (_solve_flops(entry.op, entry.m, entry.n,
                                     k - served, entry.band)
                        if k > served else 0.0)
            self.metrics.inc("flops_total", fl + waste_fl)  # executed
            self.metrics.inc("solve_flops_total", fl)       # useful
            # executed work credits the PROCESS ledger here (the api.*
            # verbs inside the compiled solve program only run at trace
            # time and deliberately credit nothing — obs.driver)
            _LEDGER.record("serve.solve", fl)
            if attr is not None:
                attr.record("solve_flops", rt, handle, fl)
            if waste_fl:
                self.metrics.inc("padding_waste_flops", waste_fl)
                self.metrics.set_gauge("width_bucket_efficiency",
                                       served / k)
                _LEDGER.record("padding.waste", waste_fl)
            if self.slo is not None:
                self.slo.record_request(entry.op, entry.n, ph.elapsed,
                                        ok=True, source="solve",
                                        tenant=rt)
            if pstats is not None:
                rnorm, xnorm, bnorm = (float(v) for v in pstats)
                if entry.anorm is None:
                    from ..core.types import Norm
                    from ..linalg.norms import norm as _norm
                    entry.anorm = float(_norm(entry.A, Norm.Inf))
                self._record_rho(
                    entry, handle,
                    _num.scaled_residual(rnorm, xnorm, bnorm,
                                         entry.anorm), served)
            return X

    def _record_rho(self, entry: _Operator, handle: Hashable,
                    rho: float, k: int):
        """Caller holds the lock and verified ``self.numerics``. One
        sampled probe's scaled residual ρ = ‖b−Ax‖/(‖A‖·‖x‖+‖b‖):
        histogram + counter + the probe gemm's model flops (a
        dedicated ``numerics.probe`` ledger op and counter — probe
        work is system work, so the tenant-conserving solve counters
        never move), the ``residual``-kind SLO event, the monitor
        record, and the health reflex on its transition."""
        self.metrics.inc("residual_probes_total")
        if np.isfinite(rho):
            self.metrics.observe("sampled_residual", rho)
        else:
            # count, don't observe: one NaN in the histogram poisons
            # sum/p99 forever and blinds the watchdog series (NaN
            # compares false against any baseline) — the monitor's
            # suspect sentinel is the alarm for this case
            self.metrics.inc("numerics_nonfinite_total")
        fl = _fl_grid(_flops_mod.gemm(entry.n, max(int(k), 1), entry.n))
        self.metrics.inc("numerics_flops_total", fl)
        self.metrics.inc("flops_total", fl)
        _LEDGER.record("numerics.probe", fl)
        if self.slo is not None:
            self.slo.record_residual(rho)
        old, new = self.numerics.record_residual(
            handle, rho, work_dtype=_work_dtype_name(entry))
        self._health_reflex(entry, handle, old, new)

    def _record_small_probe(self, entry: _Operator, handle: Hashable,
                            x: np.ndarray, b2: np.ndarray):
        """Caller holds the lock and verified ``self.numerics``. The
        small-op arm of the sampled probe: the operand is already
        host-resident (the engine's [n, n] array) and n is small by
        definition, so the residual is one host gemm — zero extra
        device programs, bit-identical between the per-request and
        grouped paths (both read the same solution bits, the
        linalg/batched contract — the health-parity pin)."""
        a = np.asarray(entry.A)
        work = np.complex128 if np.iscomplexobj(a) else np.float64
        aw = a.astype(work)
        xw = np.asarray(x).astype(work)
        bw = np.asarray(b2).astype(work)
        if bw.ndim == 1:
            # grouped 1-D rhs items arrive unsqueezed (and their
            # solutions with them); the per-request twin records the
            # (n, 1) view — same bits, same rho
            bw = bw[:, None]
        if xw.ndim == 1:
            xw = xw[:, None]
        r = bw - aw @ xw
        if entry.anorm is None:
            entry.anorm = float(np.abs(aw).sum(axis=1).max())
        rho = _num.scaled_residual(
            float(np.abs(r).max()), float(np.abs(xw).max()),
            float(np.abs(bw).max()), entry.anorm)
        self._record_rho(entry, handle, rho, bw.shape[1])

    def solve(self, handle: Hashable, b,
              served_cols: Optional[int] = None,
              tenant: Optional[str] = None) -> np.ndarray:
        """Array-in/array-out solve (the serving entry point): ``b`` is
        a host/device array of shape (rows,) or (rows, k); returns the
        solution with the matching rank (QR operators return n-row
        least-squares solutions for m-row right-hand sides).
        ``served_cols``: see solve_matrix (Batcher width padding).
        ``tenant``: per-request attribution override (round 15) —
        default is the operator's registered tenant."""
        with self._lock:
            entry = self._ops.get(handle)
            if entry is None:
                raise SlateError(f"Session: unknown handle {handle!r}")
            b = np.asarray(b)
            vector = b.ndim == 1
            b2 = b[:, None] if vector else b
            if entry.op in SMALL_OPS:
                x = self._solve_small(handle, entry, b2, tenant=tenant)
                return x[:, 0] if vector else x
            B = self._wrap_rhs(entry, b2)
            # forward served_cols/tenant only when set: solve_matrix
            # keeps its bare (handle, B) call shape on the common path
            # (test doubles and subclasses depend on it)
            kw = {}
            if served_cols is not None:
                kw["served_cols"] = served_cols
            if tenant is not None:
                kw["tenant"] = tenant
            X = self.solve_matrix(handle, B, **kw)
            x = (X.to_numpy() if isinstance(X, TiledMatrix)
                 else np.asarray(X)[: entry.n])
            return x[:, 0] if vector else x

    # -- the many-small-problems engine (round 10) -------------------------

    def small_group_key(self, handle: Hashable) -> Optional[Tuple]:
        """Grouping key for the Batcher's distinct-operator coalescing:
        (op, n, dtype) for small-problem operators, None otherwise —
        requests whose keys match can be served by ONE batched program
        regardless of which operator each one targets.

        LOCK-FREE on purpose: Batcher.submit calls this on every
        enqueue, and the session lock is held across whole device
        executions (solve/solve_small_batched) — taking it here would
        head-of-line-block enqueues behind in-flight solves, exactly
        the accumulation window batching needs. A bare dict read is
        atomic under the GIL and _Operator entries are immutable after
        register(); a concurrent unregister just yields None (the
        request then falls back to a per-handle bucket and fails with
        unknown-handle at dispatch, same as the per-request path)."""
        entry = self._ops.get(handle)
        if entry is None or entry.op not in SMALL_OPS:
            return None
        if entry.refine is not None:
            # mixed entries group only with same-policy mixed entries
            # (the policy is part of the bucket program's identity);
            # the plain key keeps its 3-tuple shape so existing
            # consumers see no change
            return (entry.op, entry.n, str(np.dtype(entry.A.dtype)),
                    entry.refine)
        return (entry.op, entry.n, str(np.dtype(entry.A.dtype)))

    def _solve_small(self, handle: Hashable, entry: _Operator,
                     b2: np.ndarray,
                     tenant: Optional[str] = None) -> np.ndarray:
        """Caller holds the lock. Per-request arm: the B=1 run of the
        same batched kernels the grouped dispatch uses (the bit-identity
        reference for the Batcher's batched path)."""
        from ..linalg import batched as _batched
        attr = self.attribution
        rt = (self.request_tenant(handle, tenant)
              if (attr is not None or self.slo is not None) else None)
        hit = handle in self._cache
        res = self.factor(handle)
        if res.info != 0:
            if self.slo is not None:
                self.slo.record_request(entry.op, entry.n, 0.0,
                                        ok=False, source="solve",
                                        tenant=rt)
            raise SlateError(
                f"Session: operator {handle!r} factorization failed "
                f"(info={res.info})")
        b2 = np.ascontiguousarray(b2, dtype=np.dtype(entry.A.dtype))
        k = b2.shape[1]
        if self.faults is not None:
            self._fault("dispatch")
        if entry.refine is not None:
            # mixed arm (round 13): one refined B=1 pass through the
            # SAME bucket programs the grouped mixed dispatch runs at
            # B=bucket; non-convergence falls back to the plain path
            # below via a working-precision refactor (counted)
            x = self._solve_small_refined(handle, entry, res, b2,
                                          tenant=rt)
            if x is not None:
                return x
            res = self.factor(handle)  # working-precision refactor
            if res.info != 0:
                raise SlateError(
                    f"Session: operator {handle!r} working-precision "
                    f"fallback factorization failed (info={res.info})")
        tr = self.tracer
        sattrs = (dict(self._span_attrs(entry, handle), k=k,
                       cache_hit=hit) if tr.enabled else {})
        with self.metrics.phase("serve.solve", "solve_latency",
                                tracer=tr, **sattrs) as ph:
            t0 = time.perf_counter()
            with tr.span("serve.dispatch"):
                if entry.op == "lu_small":
                    lu, perm = res.payload
                    x = _batched.getrs_batched(lu[None], perm[None],
                                               b2[None])
                else:
                    x = _batched.potrs_batched(res.payload[0][None],
                                               b2[None])
            t1 = time.perf_counter()
            with tr.span("serve.block"):
                x = jax.block_until_ready(x)
            t2 = time.perf_counter()
        ex = getattr(ph.span, "trace_id", None)
        self.metrics.observe("stage_dispatch", t1 - t0, exemplar=ex)
        self.metrics.observe("stage_device_execute", t2 - t1, exemplar=ex)
        self.metrics.inc("solves_total", k)
        self.metrics.inc("dispatches_total")
        fl = _solve_flops(entry.op, entry.m, entry.n, k, entry.band)
        self.metrics.inc("flops_total", fl)
        self.metrics.inc("solve_flops_total", fl)
        _LEDGER.record("serve.solve", fl)
        if attr is not None:
            attr.record("solve_flops", rt, handle, fl)
            ds = _s_grid(t2 - t1)
            self.metrics.inc("device_seconds_total", ds)
            attr.record("device_seconds", rt, handle, ds)
        if self.slo is not None:
            self.slo.record_request(entry.op, entry.n, ph.elapsed,
                                    ok=True, source="solve", tenant=rt)
        x0 = np.asarray(x[0])
        # sampled probe, per-request small arm: one sampler decision
        # per solve, in request order — the SAME stream the grouped
        # dispatch consumes per item (health-parity pin)
        if (self.numerics is not None and entry.refine is None
                and self.numerics.sampler.decide()):
            self._record_small_probe(entry, handle, x0, b2)
        return x0

    def _solve_small_refined(self, handle: Hashable, entry: _Operator,
                             res: _Resident, b2: np.ndarray,
                             tenant: Optional[str] = None
                             ) -> Optional[np.ndarray]:
        """Caller holds the lock. One refined B=1 solve from the
        resident LOW-precision factor. Returns the solution, or None
        after arming the fallback (refine deactivated, lo resident
        evicted, ``refine_fallbacks_total`` counted) — the caller then
        reruns the plain path against a working-precision refactor."""
        from ..linalg import batched as _batched
        policy = entry.refine
        a = np.asarray(entry.A)
        k = b2.shape[1]
        tr = self.tracer
        sattrs = (dict(self._span_attrs(entry, handle), k=k)
                  if tr.enabled else {})
        with self.metrics.phase("serve.solve", "solve_latency",
                                tracer=tr, **sattrs) as ph:
            t0 = time.perf_counter()
            with tr.span("serve.dispatch"):
                if entry.op == "lu_small":
                    lu, perm = res.payload
                    x, its, conv = _batched.getrs_refined_batched(
                        a[None], lu[None], perm[None], b2[None],
                        max_iters=policy.max_iters, tol=policy.tol)
                else:
                    x, its, conv = _batched.potrs_refined_batched(
                        a[None], res.payload[0][None], b2[None],
                        max_iters=policy.max_iters, tol=policy.tol)
            t1 = time.perf_counter()
            with tr.span("serve.block"):
                x, its, conv = jax.block_until_ready((x, its, conv))
            t2 = time.perf_counter()
        attr = self.attribution
        iters = int(np.asarray(its)[0])
        self.metrics.observe("refine_iterations", float(iters))
        if self.numerics is not None:
            o16, n16 = self.numerics.record_refine(handle, iters)
            self._health_reflex(entry, handle, o16, n16)
        extra = iters * (_flops_mod.gemm(entry.n, k, entry.n)
                         + _solve_flops(entry.op, entry.m, entry.n, k,
                                        entry.band))
        self.metrics.inc("refine_flops_total", extra)
        self.metrics.inc("flops_total", extra)
        _LEDGER.record("serve.refine", extra)
        if attr is not None:
            attr.record("refine_flops", tenant, handle, extra)
        if not bool(np.asarray(conv)[0]):
            self.metrics.inc("refine_fallbacks_total")
            _obs_log.warning(
                "refine fallback: small operator %r did not converge "
                "in %d iterations (factor_dtype=%s)", handle,
                policy.max_iters, policy.factor_dtype)
            rec = self.recorder
            if rec is not None:
                rec.decision("refine_fallback", op=entry.op,
                             handle=handle, tenant=tenant,
                             outcome="not_converged",
                             inputs={"iters": iters,
                                     "max_iters": policy.max_iters})
            if not policy.fallback:
                raise SlateError(
                    f"Session: refined solve of {handle!r} did not "
                    f"converge in {policy.max_iters} iterations and "
                    "the refine policy disables fallback")
            entry.refine = None
            dropped = self._cache.pop(handle, None)
            if dropped is not None:
                self.metrics.inc("evictions")
                self.metrics.inc("evicted_bytes", dropped.nbytes)
                if self.attribution is not None:
                    self._attr_evicted(handle)
                if rec is not None:
                    self._journal_evict(rec, handle, dropped.nbytes,
                                        "refine_fallback", entry=entry)
            return None
        self.metrics.inc("refine_converged_total")
        ex = getattr(ph.span, "trace_id", None)
        self.metrics.observe("stage_dispatch", t1 - t0, exemplar=ex)
        self.metrics.observe("stage_device_execute", t2 - t1,
                             exemplar=ex)
        self.metrics.inc("solves_total", k)
        self.metrics.inc("dispatches_total")
        fl = _solve_flops(entry.op, entry.m, entry.n, k, entry.band)
        self.metrics.inc("flops_total", fl)
        self.metrics.inc("solve_flops_total", fl)
        _LEDGER.record("serve.solve", fl)
        if attr is not None:
            attr.record("solve_flops", tenant, handle, fl)
            ds = _s_grid(t2 - t1)
            self.metrics.inc("device_seconds_total", ds)
            attr.record("device_seconds", tenant, handle, ds)
        if self.slo is not None:
            self.slo.record_request(entry.op, entry.n, ph.elapsed,
                                    ok=True, source="solve",
                                    tenant=tenant)
        return np.asarray(x[0])

    def solve_small_batched(self, handles: List[Hashable], bs: List,
                            tenants: Optional[List] = None
                            ) -> Tuple[np.ndarray, List[int]]:
        """ONE batched pass for a shape bucket of DISTINCT-operator
        small requests (the Batcher's grouped dispatch). Cache-miss
        operators are factored first in one batched factor program and
        the per-item factor slices inserted into the cache (bit-identical
        to the B=1 factors the per-request path would have cached —
        batch-independent kernels); then every request's factor is
        stacked — resident hits and fresh misses alike — and served by
        one batched solve program. Returns ``(xs, infos)``: solutions
        ``[B, rows, k]`` in request order plus per-item factorization
        info — a singular item flags itself, its lane carries the
        garbage, and its neighbors' bits are untouched (per-item
        isolation, pinned by tests/test_batched.py).

        Observability: ``batched_programs`` counts the batched programs
        executed (≤ 2 per bucket: factor for the misses, solve for
        everyone — vs O(B) per-request programs), ``bucket_occupancy``
        records the pow2-bucket fill fraction, and the flop ledger is
        credited B × the per-item serve models."""
        from ..linalg import batched as _batched
        if not handles or len(handles) != len(bs):
            raise SlateError("solve_small_batched: handles and bs must "
                             "be equal-length and nonempty")
        if tenants is not None and len(tenants) != len(handles):
            raise SlateError("solve_small_batched: tenants must match "
                             "handles in length")
        with self._lock:
            entries = []
            for h in handles:
                e = self._ops.get(h)
                if e is None:
                    raise SlateError(f"Session: unknown handle {h!r}")
                if e.op not in SMALL_OPS:
                    raise SlateError(
                        f"solve_small_batched: {h!r} is op {e.op!r}, "
                        "not a small-problem operator")
                entries.append(e)
            op, n = entries[0].op, entries[0].n
            dt = np.dtype(entries[0].A.dtype)
            for e in entries[1:]:
                if e.op != op or e.n != n or np.dtype(e.A.dtype) != dt:
                    raise SlateError(
                        "solve_small_batched: mixed bucket (op/n/dtype "
                        "must agree across the batch)")
            pol = entries[0].refine
            if any(e.refine != pol for e in entries[1:]):
                # a refine fallback deactivated one handle's policy
                # between enqueue (lock-free grouping) and dispatch —
                # rare race; serve the bucket per-request, correctness
                # over coalescing
                return self._serve_small_per_request(handles, bs,
                                                     tenants=tenants)
            bsz = len(handles)
            # round 15: per-item request tenants (override -> operator
            # tenant -> default), resolved once — the grouped dispatch
            # must produce the SAME tenant-labeled tallies B
            # per-request solves would (the satellite-1 parity pin)
            attr = self.attribution
            rts = None
            if attr is not None or self.slo is not None:
                rts = [self.request_tenant(
                    h, None if tenants is None else tenants[i])
                    for i, h in enumerate(handles)]
            tr = self.tracer
            battrs = ({"op": op, "n": n, "batch": bsz, "dtype": str(dt)}
                      if tr.enabled else {})
            programs = 0
            # residency BEFORE factoring: a request against an operator
            # that was already resident counts a cache hit, everything
            # else a miss — the same tallies B per-request solves give
            was_resident = {h: (h in self._cache) for h in set(handles)}
            if self.faults is not None:
                self._fault("dispatch")
            with self.metrics.phase("serve.solve_batched",
                                    "solve_latency", tracer=tr,
                                    **battrs) as ph:
                miss_handles = []
                for h in handles:
                    if not was_resident[h] and h not in miss_handles:
                        miss_handles.append(h)
                if miss_handles:
                    amiss = np.stack([np.asarray(self._ops[h].A)
                                      for h in miss_handles])
                    with tr.span("serve.factor_batched",
                                 batch=len(miss_handles)):
                        if pol is not None and op == "lu_small":
                            lus, perms, infos = \
                                _batched.getrf_mixed_batched(
                                    amiss, pol.factor_dtype)
                            lus, perms, infos = jax.block_until_ready(
                                (lus, perms, infos))
                            payloads = [(lus[i], perms[i])
                                        for i in range(len(miss_handles))]
                        elif pol is not None:
                            ls, infos = _batched.potrf_mixed_batched(
                                amiss, pol.factor_dtype)
                            ls, infos = jax.block_until_ready((ls, infos))
                            payloads = [(ls[i],)
                                        for i in range(len(miss_handles))]
                        elif op == "lu_small":
                            lus, perms, infos = _batched.getrf_batched(
                                amiss)
                            lus, perms, infos = jax.block_until_ready(
                                (lus, perms, infos))
                            payloads = [(lus[i], perms[i])
                                        for i in range(len(miss_handles))]
                        else:
                            ls, infos = _batched.potrf_batched(amiss)
                            ls, infos = jax.block_until_ready((ls, infos))
                            payloads = [(ls[i],)
                                        for i in range(len(miss_handles))]
                    if pol is not None and any(int(v) != 0
                                               for v in np.asarray(infos)):
                        # a LOW-precision batched factor failed (e.g.
                        # SPD goes indefinite under bf16 rounding): do
                        # NOT cache the bad lo residents — serve the
                        # bucket per-request, where Session.factor owns
                        # the counted working-precision fallback (the
                        # per-request parity contract: a recoverable
                        # lo-factor failure must not fail futures or
                        # poison the cache)
                        return self._serve_small_per_request(
                            handles, bs, tenants=tenants)
                    ffl = _factor_flops(op, n, n, 0)
                    for h, payload, inf in zip(miss_handles, payloads,
                                               infos):
                        res_h = _Resident(payload, int(inf),
                                          _tree_nbytes(payload))
                        self._cache[h] = res_h
                        self.metrics.inc("factors_total")
                        self.metrics.inc("flops_total", ffl)
                        self.metrics.inc("factor_flops_total", ffl)
                        _LEDGER.record("serve.factor", ffl)
                        if attr is not None:
                            # factor work belongs to the operator's
                            # tenant (the per-request path's factor()
                            # convention — tenant-labeled parity);
                            # the accrual return conserves the seam
                            # by construction (0 on a true miss)
                            ot = self._ops[h].tenant
                            attr.record("factor_flops", ot, h, ffl)
                            inc = attr.touch_residency(ot, h,
                                                       res_h.nbytes)
                            if inc:
                                self.metrics.inc(
                                    "residency_byte_seconds_total",
                                    inc)
                        self._evict_to_budget(keep=h)
                        if self.tenant_policies is not None:
                            self._evict_tenant_to_budget(
                                self._ops[h].tenant, keep=h)
                    programs += 1
                # per-request residents, in request order (the budget
                # can in principle evict a just-inserted factor while
                # later misses insert; self.factor refactors that item
                # at B=1 — same bits, counted as one more miss).
                # Duplicate handles: only the FIRST request against a
                # cold handle is a miss — its duplicates hit the factor
                # it just inserted, exactly the tallies B sequential
                # per-request solves give (1 miss + B−1 hits).
                res_list = []
                counted_miss = set()
                for h in handles:
                    if was_resident[h] or h in counted_miss:
                        self.metrics.inc("cache_hits")
                        if attr is not None:
                            # same tenant-labeled hit tally (and heat
                            # advance / residency touch) B per-request
                            # solves would record — 1 miss + B−1 hits
                            # per cold duplicate handle, pinned
                            ot = self._ops[h].tenant
                            attr.access(ot, h, True)
                            res_t = self._cache.get(h)
                            if res_t is not None:
                                inc = attr.touch_residency(
                                    ot, h, res_t.nbytes)
                                if inc:
                                    self.metrics.inc(
                                        "residency_byte_seconds_total",
                                        inc)
                        if self.slo is not None:
                            self.slo.record_cache(True)
                        if h in self._cache:
                            self._cache.move_to_end(h)
                    else:
                        self.metrics.inc("cache_misses")
                        if attr is not None:
                            attr.access(self._ops[h].tenant, h, False)
                        if self.slo is not None:
                            self.slo.record_cache(False)
                        counted_miss.add(h)
                    res = self._cache.get(h)
                    if res is None:
                        res = self.factor(h)
                    res_list.append(res)
                infos_req = [r.info for r in res_list]
                import jax.numpy as jnp
                bstack = np.stack([
                    np.ascontiguousarray(np.asarray(b), dtype=dt)
                    for b in bs])
                its = conv = None
                t0 = time.perf_counter()
                with tr.span("serve.dispatch", batch=bsz):
                    if pol is not None:
                        # mixed bucket: ONE batched refined solve over
                        # the stacked LOW-precision residents, per-item
                        # convergence masks (refine/engine); the
                        # working-precision operands feed the residual
                        # gemms
                        astack = np.stack([np.asarray(e.A)
                                           for e in entries])
                        if op == "lu_small":
                            x, its, conv = _batched.getrs_refined_batched(
                                astack,
                                jnp.stack([r.payload[0]
                                           for r in res_list]),
                                jnp.stack([r.payload[1]
                                           for r in res_list]),
                                bstack, max_iters=pol.max_iters,
                                tol=pol.tol)
                        else:
                            x, its, conv = _batched.potrs_refined_batched(
                                astack,
                                jnp.stack([r.payload[0]
                                           for r in res_list]),
                                bstack, max_iters=pol.max_iters,
                                tol=pol.tol)
                    elif op == "lu_small":
                        x = _batched.getrs_batched(
                            jnp.stack([r.payload[0] for r in res_list]),
                            jnp.stack([r.payload[1] for r in res_list]),
                            bstack)
                    else:
                        x = _batched.potrs_batched(
                            jnp.stack([r.payload[0] for r in res_list]),
                            bstack)
                t1 = time.perf_counter()
                with tr.span("serve.block"):
                    x = jax.block_until_ready(x)
                t2 = time.perf_counter()
                programs += 1
                if pol is not None:
                    # np.array (writable copy), not asarray: the
                    # per-item fallback below splices lanes in place
                    x, its, conv = (np.array(x), np.asarray(its),
                                    np.asarray(conv))
                    for i in range(bsz):
                        self.metrics.observe("refine_iterations",
                                             float(its[i]))
                        if self.numerics is not None:
                            # per-item refine drift (round 16): the
                            # grouped mixed bucket records the SAME
                            # per-handle iteration stream B per-request
                            # refined solves would
                            o16, n16 = self.numerics.record_refine(
                                handles[i], int(its[i]))
                            self._health_reflex(entries[i], handles[i],
                                                o16, n16)
                    kk = bstack.shape[2] if bstack.ndim == 3 else 1
                    # per-item refinement flops (iters_i × one step's
                    # residual gemm + factor apply, integer grid), so
                    # the global credit below is EXACTLY the sum of
                    # the tenant-attributed per-item values — the
                    # mixed-lane arm of the satellite-1 parity pin
                    per_step = (_flops_mod.gemm(n, kk, n)
                                + _solve_flops(op, n, n, kk, 0))
                    extra_i = [float(int(its[i])) * per_step
                               for i in range(bsz)]
                    extra = float(sum(extra_i))
                    self.metrics.inc("refine_flops_total", extra)
                    self.metrics.inc("flops_total", extra)
                    _LEDGER.record("serve.refine", extra)
                    if attr is not None:
                        for i in range(bsz):
                            if extra_i[i]:
                                attr.record("refine_flops", rts[i],
                                            handles[i], extra_i[i])
                    self.metrics.inc(
                        "refine_converged_total",
                        int(conv.sum()))
                    for i in range(bsz):
                        if conv[i] or infos_req[i] != 0:
                            continue
                        # per-item fallback: deactivate refinement for
                        # this handle, evict its lo resident, refactor
                        # at working precision, re-solve item i alone —
                        # its bucket neighbors' lanes are untouched
                        h = handles[i]
                        e = self._ops[h]
                        self.metrics.inc("refine_fallbacks_total")
                        _obs_log.warning(
                            "refine fallback: grouped small operator %r "
                            "did not converge in %d iterations", h,
                            pol.max_iters)
                        rec = self.recorder
                        if rec is not None:
                            rec.decision(
                                "refine_fallback", op=e.op, handle=h,
                                tenant=e.tenant,
                                outcome="not_converged",
                                inputs={"max_iters": pol.max_iters,
                                        "grouped": True})
                        if not pol.fallback:
                            raise SlateError(
                                f"Session: refined solve of {h!r} did "
                                "not converge and the refine policy "
                                "disables fallback")
                        if e.refine is not None:
                            e.refine = None
                            dropped = self._cache.pop(h, None)
                            if dropped is not None:
                                self.metrics.inc("evictions")
                                self.metrics.inc("evicted_bytes",
                                                 dropped.nbytes)
                                if self.attribution is not None:
                                    self._attr_evicted(h)
                                if rec is not None:
                                    self._journal_evict(
                                        rec, h, dropped.nbytes,
                                        "refine_fallback", entry=e)
                        res_i = self.factor(h)
                        infos_req[i] = res_i.info
                        if res_i.info != 0:
                            continue
                        if op == "lu_small":
                            lu_i, perm_i = res_i.payload
                            xi = _batched.getrs_batched(
                                lu_i[None], perm_i[None], bstack[i][None])
                        else:
                            xi = _batched.potrs_batched(
                                res_i.payload[0][None], bstack[i][None])
                        x[i] = np.asarray(jax.block_until_ready(xi))[0]
            if self.numerics is not None and pol is None:
                # sampled probe, grouped arm: one sampler decision per
                # SUCCESSFUL item in request order (a failed item's
                # per-request twin raises at the info check before its
                # probe, consuming nothing — so the grouped arm must
                # skip it too or every later decision shifts), the
                # residual from the same host gemm the per-request
                # probe runs on the same solution bits — parity pinned
                xs_np = None
                for i in range(bsz):
                    if infos_req[i] != 0:
                        continue
                    if self.numerics.sampler.decide():
                        if xs_np is None:
                            xs_np = np.asarray(x)
                        self._record_small_probe(entries[i], handles[i],
                                                 xs_np[i], bstack[i])
            ex = getattr(ph.span, "trace_id", None)
            self.metrics.observe("stage_dispatch", t1 - t0, exemplar=ex)
            self.metrics.observe("stage_device_execute", t2 - t1,
                                 exemplar=ex)
            k = bstack.shape[2] if bstack.ndim == 3 else 1
            bucket = _batched.batch_bucket(bsz)
            self.metrics.inc("solves_total", bsz * k)
            self.metrics.inc("dispatches_total")
            self.metrics.inc("batched_programs", programs)
            self.metrics.observe("bucket_occupancy", bsz / bucket)
            per_sfl = _solve_flops(op, n, n, k, 0)
            sfl = bsz * per_sfl
            self.metrics.inc("flops_total", sfl)
            self.metrics.inc("solve_flops_total", sfl)
            _LEDGER.record("serve.solve", sfl)
            if attr is not None:
                # per-item solve flops (global sfl = bsz × per_sfl is
                # exactly their sum on the integer grid) and the
                # batch's device-execute seconds split across items in
                # 2^-20 s grid units — integer division, remainder to
                # the first item, so the per-tenant shares sum
                # BIT-EXACTLY to the global credit
                units = round((t2 - t1) * float(1 << 20))
                share, rem = divmod(int(units), bsz)
                self.metrics.inc("device_seconds_total",
                                 units / float(1 << 20))
                for i in range(bsz):
                    attr.record("solve_flops", rts[i], handles[i],
                                per_sfl)
                    ds_i = (share + (rem if i == 0 else 0)) \
                        / float(1 << 20)
                    if ds_i:
                        attr.record("device_seconds", rts[i],
                                    handles[i], ds_i)
            # padding-waste counters (round 12): the pow2 batch bucket
            # executes bucket − bsz REAL padded lanes (identity
            # operands, zero rhs) in the solve program — and the miss
            # factor program its own bucket's padding. The PROCESS
            # ledger's padding.waste op is credited at the source
            # (linalg/batched pads there); these are the session-level
            # /metrics counters. Exactly 0 at full pow2 occupancy.
            waste_fl = (bucket - bsz) * _solve_flops(op, n, n, k, 0)
            if miss_handles:
                fbucket = _batched.batch_bucket(len(miss_handles))
                waste_fl += ((fbucket - len(miss_handles))
                             * _factor_flops(op, n, n, 0))
            if waste_fl:
                self.metrics.inc("padding_waste_flops", waste_fl)
            self.metrics.set_gauge("batch_bucket_efficiency", bsz / bucket)
            if self.slo is not None:
                for i, inf in enumerate(infos_req):
                    self.slo.record_request(op, n, ph.elapsed,
                                            ok=(inf == 0), source="solve",
                                            tenant=(None if rts is None
                                                    else rts[i]))
            return np.asarray(x), infos_req

    def _serve_small_per_request(self, handles: List[Hashable],
                                 bs: List,
                                 tenants: Optional[List] = None
                                 ) -> Tuple[np.ndarray, List[int]]:
        """Caller holds the lock. Degraded grouped dispatch: each
        request through the per-request path — correctness over
        coalescing, used when the one-program pass is unsafe (a
        stale-policy race after a refine fallback, or a failed
        low-precision batched factor whose lanes must take the
        per-request fallback instead of being cached). Per-item
        isolation: an item whose own solve fails carries its nonzero
        info; neighbors are served normally."""
        xs, infos = [], []
        for i, (h, b) in enumerate(zip(handles, bs)):
            e = self._ops[h]
            b2 = np.ascontiguousarray(np.asarray(b),
                                      dtype=np.dtype(e.A.dtype))
            if b2.ndim == 1:
                b2 = b2[:, None]
            try:
                xs.append(self._solve_small(
                    h, e, b2,
                    tenant=None if tenants is None else tenants[i]))
                infos.append(0)
            except SlateError:
                res = self._cache.get(h)
                infos.append(int(res.info) if res is not None
                             and res.info else 1)
                xs.append(np.zeros_like(b2))
        return np.stack(xs), infos

    def _wrap_rhs(self, entry: _Operator, b2: np.ndarray):
        dtype = (entry.A.dtype if not isinstance(entry.A, PackedBand)
                 else entry.A.ab.dtype)
        b2 = np.ascontiguousarray(b2, dtype=np.dtype(dtype))
        if entry.op in ("band_lu", "band_chol"):
            return jax.numpy.asarray(b2)
        nb = entry.A.nb
        # mesh operators get a mesh-placed right-hand side (grid=None
        # is the single-device no-op): the solve program then consumes
        # sharded inputs end to end instead of all-gathering at entry
        return from_dense(b2, nb=nb, grid=entry.grid)

    def _dispatch(self, entry: _Operator, res: _Resident, B,
                  handle: Hashable = None,
                  served_cols: Optional[int] = None,
                  tenant: Optional[str] = None):
        """Run the solve through a per-(op, opts) jitted function,
        preferring an AOT-compiled executable from warmup() when shapes
        match. opts is part of both cache keys: two operators of the
        same kind registered with different Options (precision, method
        selection) must not share a closure.

        Mesh entries NEVER take the plain-jit fallback: a shape warmup
        missed is AOT-compiled here (one sharded program per (op,
        shapes, dtype, mesh) — the mesh is part of the key via the
        operand treedefs), so every served mesh solve executes an
        analyzed program and credits its collective census."""
        if entry.refine is not None:
            return self._dispatch_refined(entry, res, B, handle,
                                          served_cols=served_cols,
                                          tenant=tenant)
        fn = self._solve_fn(entry)
        key = self._aot_key(entry, res.payload, B)
        exe = self._compiled.get(key)
        if exe is None and entry.grid is not None:
            exe = self._aot_compile("solve", entry, handle, fn,
                                    (res.payload, B), key=key)
            self._compiled_put(key, exe)
            self.metrics.inc("aot_compiles")
        if exe is not None:
            self._compiled.move_to_end(key)
            k = int(B.shape[1]) if getattr(B, "shape", None) else 0
            wf = (0.0 if served_cols is None or not k
                  else (k - served_cols) / k)
            self._credit_program(key, "serve.solve", waste_fraction=wf,
                                 tenant=tenant, handle=handle)
            return exe(res.payload, B)
        return fn(res.payload, B)

    def _solve_fn(self, entry: _Operator):
        return self._jit_cached(
            (entry.op, entry.opts),
            lambda: _make_solve_fn(entry.op, entry.opts))

    # -- sampled residual probe (round 16, obs/numerics.py) ----------------

    def _probe_exe(self, entry: _Operator, handle: Hashable,
                   args: Tuple):
        """AOT-compiled fused solve+residual program for these shapes
        → (exe, key) — the _refine_exe discipline: always analyzed, so
        probed solves credit bytes/census per execution and the budget
        sees the program's transient. Warmup precompiles the
        (m, nrhs) shape; other logical rhs widths compile on their
        first probed use (counted in ``aot_compiles`` — the fused
        norms read the logical extent, so the program is genuinely
        per-width, unlike the plain solve's jit fallback)."""
        leaves, treedef = jax.tree_util.tree_flatten(args)
        shapes = tuple((tuple(l.shape), str(l.dtype)) for l in leaves)
        key = ("probe", entry.op, entry.opts, treedef, shapes)
        exe = self._compiled.get(key)
        if exe is None:
            fn = self._jit_cached(
                ("probe", entry.op, entry.opts),
                lambda: _make_probe_fn(entry.op, entry.opts))
            exe = self._aot_compile("probe", entry, handle, fn, args,
                                    key=key)
            self._compiled_put(key, exe)
            self.metrics.inc("aot_compiles")
        else:
            self._compiled.move_to_end(key)
        return exe, key

    def _dispatch_probed(self, entry: _Operator, res: _Resident, B,
                         handle: Hashable = None,
                         served_cols: Optional[int] = None,
                         tenant: Optional[str] = None):
        """One PROBED dispatch: the serving solve fused with the
        residual gemm and the (‖b−Ax‖, ‖x‖, ‖b‖) max-norm triple in
        ONE program — exactly one gemm more than the plain solve
        program (HLO-pinned by test), executed and credited like every
        other served program. Returns (X, stats)."""
        args = (res.payload, entry.A, B)
        exe, key = self._probe_exe(entry, handle, args)
        k = int(B.shape[1]) if getattr(B, "shape", None) else 0
        wf = (0.0 if served_cols is None or not k
              else (k - served_cols) / k)
        self._credit_program(key, "serve.solve", waste_fraction=wf,
                             tenant=tenant, handle=handle)
        return exe(*args)

    # -- resident spectral serving (round 19, slate_tpu/spectral/) ---------

    @staticmethod
    def _spectral_theta(entry: _Operator, theta) -> np.ndarray:
        """The traced scalar parameter of a served matrix function, at
        a FIXED dtype (the operand's real dtype) so every theta value
        reuses one AOT program — a new shift/ridge/rank never
        recompiles (the zero-new-compiles pin)."""
        rdt = np.zeros((), np.dtype(entry.A.dtype)).real.dtype
        return np.asarray(theta, dtype=rdt)

    def _spectral_apply_exe(self, entry: _Operator, handle: Hashable,
                            fname: str, args: Tuple):
        """AOT-compiled served apply for these shapes → (exe, key).
        ALWAYS through the ``_aot_compile`` seam (the refined-entry
        discipline): every served spectral apply executes an analyzed
        program — exactly two gemms + a diagonal scale (HLO-pinned by
        test) — so bytes/census credit per execution."""
        from .. import spectral as _spectral
        leaves, treedef = jax.tree_util.tree_flatten(args)
        shapes = tuple((tuple(l.shape), str(l.dtype)) for l in leaves)
        key = ("spectral.apply", fname, entry.op, entry.opts, treedef,
               shapes)
        exe = self._compiled.get(key)
        if exe is None:
            fn = self._jit_cached(
                ("spectral.apply", entry.op, fname, entry.opts),
                lambda: _spectral.make_apply_fn(entry.op, fname,
                                                entry.opts))
            exe = self._aot_compile("apply", entry, handle, fn, args,
                                    key=key)
            self._compiled_put(key, exe)
            self.metrics.inc("aot_compiles")
        else:
            self._compiled.move_to_end(key)
        return exe, key

    def _dispatch_spectral(self, entry: _Operator, res: _Resident, B,
                           handle: Hashable = None,
                           fname: str = "solve", theta: float = 0.0,
                           served_cols: Optional[int] = None,
                           tenant: Optional[str] = None):
        """One served spectral apply: X = L·diag(f(spectrum, θ))·Rᴴ·B
        against the resident decomposition."""
        args = (res.payload, B, self._spectral_theta(entry, theta))
        exe, key = self._spectral_apply_exe(entry, handle, fname, args)
        k = int(B.shape[1]) if getattr(B, "shape", None) else 0
        wf = (0.0 if served_cols is None or not k
              else (k - served_cols) / k)
        self._credit_program(key, "serve.solve", waste_fraction=wf,
                             tenant=tenant, handle=handle)
        return exe(*args)

    def _spectral_probe(self, entry: _Operator, res: _Resident, B,
                        handle: Hashable):
        """Caller holds the lock. The sampled spectral residual probe:
        one analyzed single-gemm program computing
        ‖A·v_i − λ_i·v_i‖_max (svd: ‖A·v_i − σ_i·u_i‖_max) over a
        static sample of extreme columns → the stacked max-norm triple
        the shared ρ post-processing consumes."""
        from .. import spectral as _spectral
        args = (res.payload, entry.A)
        leaves, treedef = jax.tree_util.tree_flatten(args)
        shapes = tuple((tuple(l.shape), str(l.dtype)) for l in leaves)
        key = ("spectral.probe", entry.op, entry.opts, treedef, shapes)
        exe = self._compiled.get(key)
        if exe is None:
            fn = self._jit_cached(
                ("spectral.probe", entry.op, entry.opts),
                lambda: _spectral.make_probe_fn(entry.op, entry.opts))
            exe = self._aot_compile("probe", entry, handle, fn, args,
                                    key=key)
            self._compiled_put(key, exe)
            self.metrics.inc("aot_compiles")
        else:
            self._compiled.move_to_end(key)
        self._credit_program(key, "numerics.probe", tenant=entry.tenant,
                             handle=handle)
        return exe(*args)

    def apply(self, handle: Hashable, b, fn: str = "solve",
              theta: float = 0.0, served_cols: Optional[int] = None,
              tenant: Optional[str] = None) -> np.ndarray:
        """Served matrix function of a resident spectral operator:
        x = f(A)·b — solve-with-shift ((A−θI)⁻¹b), psd_project,
        whiten, truncate (see spectral/types.py for the per-op
        catalogs). Array-in/array-out like :meth:`solve`; ``theta`` is
        the function's scalar parameter, traced so any value reuses
        the warmed program. svd note: forward functions (truncate)
        take n-row right-hand sides; inverse-direction functions
        (solve/whiten) take m-row ones."""
        from .. import spectral as _spectral
        with self._lock:
            entry = self._ops.get(handle)
            if entry is None:
                raise SlateError(f"Session: unknown handle {handle!r}")
            if entry.op not in SPECTRAL_OPS:
                raise SlateError(
                    f"Session.apply: operator {handle!r} is "
                    f"{entry.op!r}, not a spectral (eig/svd) resident")
            catalog = _spectral.function_catalog(entry.op)
            if fn not in catalog:
                raise SlateError(
                    f"Session.apply: unknown function {fn!r} for op "
                    f"{entry.op!r}; served functions: "
                    f"{sorted(catalog)}")
            b = np.asarray(b)
            vector = b.ndim == 1
            b2 = b[:, None] if vector else b
            B = self._wrap_rhs(entry, b2)
            kw = {}
            if served_cols is not None:
                kw["served_cols"] = served_cols
            if tenant is not None:
                kw["tenant"] = tenant
            X = self.solve_matrix(handle, B, spectral_fn=fn,
                                  theta=theta, **kw)
            x = X.to_numpy()
            return x[:, 0] if vector else x

    def eigvals(self, handle: Hashable) -> np.ndarray:
        """The resident spectrum: Λ ascending for ``eig`` operators,
        Σ descending for ``svd`` (factoring on miss — a spectrum read
        is a serve and warms the resident like any other)."""
        with self._lock:
            entry = self._ops.get(handle)
            if entry is None:
                raise SlateError(f"Session: unknown handle {handle!r}")
            if entry.op not in SPECTRAL_OPS:
                raise SlateError(
                    f"Session.eigvals: operator {handle!r} is "
                    f"{entry.op!r}, not a spectral (eig/svd) resident")
            res = self.factor(handle)
            if res.info != 0:
                raise SlateError(
                    f"Session: operator {handle!r} factorization "
                    f"failed (info={res.info})")
            p = res.payload
            return np.asarray(p.lam if entry.op == "eig" else p.s)

    # -- mixed-precision refined dispatch (round 13, slate_tpu/refine/) ----

    def _refine_exe(self, entry: _Operator, handle: Hashable, what: str,
                    args: Tuple):
        """AOT-compiled refine ``start``/``step`` program for these
        argument shapes → (exe, key). ALWAYS through the ``_aot_compile``
        seam (like mesh entries): every refined solve executes analyzed
        programs, so bytes/census credit per execution and the budget
        sees the programs' transients."""
        policy = entry.refine
        leaves, treedef = jax.tree_util.tree_flatten(args)
        shapes = tuple((tuple(l.shape), str(l.dtype)) for l in leaves)
        key = (f"refine.{what}", entry.op, entry.opts, policy, treedef,
               shapes)
        exe = self._compiled.get(key)
        if exe is None:
            work = entry.A.dtype
            make = (_refine_engine.make_start_fn if what == "start"
                    else _refine_engine.make_step_fn)
            fn = self._jit_cached(
                (f"refine.{what}", entry.op, entry.opts, policy),
                lambda: make(entry.op, entry.opts, policy, work))
            exe = self._aot_compile(f"refine_{what}", entry, handle, fn,
                                    args, key=key)
            self._compiled_put(key, exe)
            self.metrics.inc("aot_compiles")
        else:
            self._compiled.move_to_end(key)
        return exe, key

    def _dispatch_refined(self, entry: _Operator, res: _Resident, B,
                          handle: Hashable = None,
                          served_cols: Optional[int] = None,
                          tenant: Optional[str] = None):
        """Serve one solve from the LOW-precision resident: initial lo
        solve + the refine engine's convergence loop over analyzed
        start/step programs (classic IR) or the GMRES-IR cycle. Emits
        ``refine.*`` spans nested under the solve span, observes the
        per-solve iteration count, splits the ledger useful-vs-
        refinement (``served_cols`` — the Batcher's pow2 width padding
        — splits the programs' bytes to ``padding.waste`` exactly like
        the plain dispatch), and turns non-convergence into the counted
        fallback: evict the lo resident, refactor at working precision
        through the normal path, re-dispatch — never a wrong answer."""
        policy = entry.refine
        tr = self.tracer
        k = int(B.shape[1])
        wf = (0.0 if served_cols is None or not k
              else (k - int(served_cols)) / k)
        if entry.anorm is None:
            from ..core.types import Norm
            from ..linalg.norms import norm as _norm
            entry.anorm = float(_norm(entry.A, Norm.Inf))
        if policy.strategy == "gmres":
            with tr.span("refine.gmres", max_iters=policy.max_iters):
                X, iters, converged = _refine_engine.gmres_solve(
                    entry.A, B, res.payload, entry.op, policy,
                    entry.opts)
        else:
            start_exe, start_key = self._refine_exe(
                entry, handle, "start", (res.payload, B))
            state = {}

            def start_call(payload, B_):
                with tr.span("refine.start"):
                    X0 = start_exe(payload, B_)
                self._credit_program(start_key, "serve.solve",
                                     waste_fraction=wf,
                                     tenant=tenant, handle=handle)
                return X0

            def step_call(payload, A_, B_, X_):
                exe = state.get("exe")
                if exe is None:
                    exe, skey = self._refine_exe(
                        entry, handle, "step", (payload, A_, B_, X_))
                    state["exe"], state["key"] = exe, skey
                with tr.span("refine.step"):
                    out = exe(payload, A_, B_, X_)
                self._credit_program(state["key"], "serve.refine",
                                     waste_fraction=wf,
                                     tenant=tenant, handle=handle)
                return out

            X, iters, converged = _refine_engine.drive(
                start_call, step_call, res.payload, entry.A, B,
                entry.anorm, policy, entry.A.dtype,
                fault_hook=(None if self.faults is None else
                            (lambda: bool(self._fault(
                                "refine.converge")))))
        self.metrics.observe("refine_iterations", float(iters))
        if self.numerics is not None:
            # refine-iteration drift (round 16): rising iteration
            # counts at fixed tolerance = u_f·κ grew — the
            # conditioning-degradation proxy per handle
            o16, n16 = self.numerics.record_refine(handle, iters)
            self._health_reflex(entry, handle, o16, n16)
        # refinement-overhead model flops: iters residual gemms plus
        # iters factor applies (the useful one-solve model stays on
        # serve.solve — ledger split, ISSUE 10 observability)
        extra = iters * (_flops_mod.gemm(entry.n, k, entry.n)
                         + _solve_flops(entry.op, entry.m, entry.n, k,
                                        entry.band))
        self.metrics.inc("refine_flops_total", extra)
        self.metrics.inc("flops_total", extra)
        _LEDGER.record("serve.refine", extra)
        if self.attribution is not None and extra:
            self.attribution.record("refine_flops", tenant, handle,
                                    extra)
        if converged:
            self.metrics.inc("refine_converged_total")
            return X
        self.metrics.inc("refine_fallbacks_total")
        _obs_log.warning(
            "refine fallback: %r did not converge in %d iterations "
            "(factor_dtype=%s, strategy=%s); refactoring at working "
            "precision", handle, policy.max_iters, policy.factor_dtype,
            policy.strategy)
        rec = self.recorder
        if rec is not None:
            rec.decision("refine_fallback", op=entry.op, handle=handle,
                         tenant=tenant, outcome="not_converged",
                         inputs={"iters": iters,
                                 "max_iters": policy.max_iters,
                                 "strategy": policy.strategy})
        if tr.enabled:
            with tr.span("refine.fallback", handle=repr(handle),
                         iters=iters):
                pass
        if not policy.fallback:
            raise SlateError(
                f"Session: refined solve of {handle!r} did not converge "
                f"in {policy.max_iters} iterations and the refine "
                "policy disables fallback")
        entry.refine = None
        dropped = self._cache.pop(handle, None)
        if dropped is not None:
            self.metrics.inc("evictions")
            self.metrics.inc("evicted_bytes", dropped.nbytes)
            if self.attribution is not None:
                self._attr_evicted(handle)
            if rec is not None:
                self._journal_evict(rec, handle, dropped.nbytes,
                                    "refine_fallback", entry=entry)
        res2 = self.factor(handle)
        if res2.info != 0:
            raise SlateError(
                f"Session: operator {handle!r} working-precision "
                f"fallback factorization failed (info={res2.info})")
        return self._dispatch(entry, res2, B, handle,
                              served_cols=served_cols, tenant=tenant)

    # -- incremental factor maintenance (round 20, linalg/update.py) -------

    def update(self, handle: Hashable, delta=None, *,
               downdate: bool = False, delete=None,
               tenant: Optional[str] = None) -> dict:
        """Serve an operand mutation against the RESIDENT factor at
        O(n²k) instead of paying the O(n³) refactor (round 20,
        linalg/update.py — GGMS C1/C2/Q4, Davis–Hager sweep):

        * ``chol``/``chol_small``: ``delta`` is the (n, k) vector block
          W of A' = A + W·Wᴴ (``downdate=True`` for A − W·Wᴴ; the
          positivity guard degrades a failed downdate to a counted
          refactor of the committed operand — never a wrong factor);
        * ``qr``: ``delta`` is (p, n) rows to APPEND, or ``delete=``
          row indices to remove (incremental for previously appended
          rows; deleting a base row degrades to a counted refactor).

        The mutated operand is committed either way — on every
        degraded path the refactor answers from A', so the caller's
        view of the operator is always the post-mutation one. Ranks
        and appended-row counts are padded to pow2 buckets (zero
        lanes are exactly inert), so a stream of k = 1..16 updates
        compiles O(log k) programs through the same ``_aot_compile``
        census seam as every serving program.

        Returns a result dict: ``applied`` (the incremental path
        served it), ``refactored`` (a counted refactor ran — abort
        fault, failed downdate, base-row delete, or the numerics
        update budget coming due), ``deferred`` (no resident to
        maintain: the mutation committed, the next factor() is a
        plain miss), plus ``info``/``k``/``k_bucket``."""
        with self._lock:
            entry = self._ops.get(handle)
            if entry is None:
                raise SlateError(f"Session: unknown handle {handle!r}")
            if entry.op not in UPDATE_OPS:
                raise SlateError(
                    f"Session.update: operator kind {entry.op!r} has "
                    f"no incremental form (supported: {UPDATE_OPS}); "
                    "re-register the mutated operand instead")
            if entry.grid is not None:
                raise SlateError(
                    "Session.update: mesh residents refactor, they do "
                    "not update (the rotation sweep is sequential in "
                    "columns — no profitable sharding)")
            if entry.op == "qr":
                return self._update_qr(entry, handle, delta, delete,
                                       tenant)
            if delete is not None:
                raise SlateError("Session.update: delete= applies to "
                                 "qr operators only")
            return self._update_chol(entry, handle, delta, downdate,
                                     tenant)

    def _request_tenant_or_none(self, handle: Hashable,
                                tenant: Optional[str]) -> Optional[str]:
        """Caller holds the lock: resolved tenant when attribution
        needs one (the request_tenant rule), else the raw override."""
        if self.attribution is not None:
            return self.request_tenant(handle, tenant)
        return tenant

    def _update_chol(self, entry: _Operator, handle: Hashable, delta,
                     downdate: bool, tenant: Optional[str]) -> dict:
        """Caller holds the lock. Rank-k A' = A ± W·Wᴴ against the
        resident potrf factor: the dense path runs the AOT-compiled
        rotation sweep; the small-engine path runs the B=1 slice of
        the SAME batched sweep the grouped verb uses (bit-identical
        by construction, the round-10 rule)."""
        import jax.numpy as jnp
        from ..linalg import update as _upd
        if delta is None:
            raise SlateError("Session.update: chol update needs delta "
                             "(the (n, k) update-vector block W)")
        small = entry.op == "chol_small"
        wd = np.dtype(entry.A.dtype)
        w = np.asarray(delta)
        if w.ndim == 1:
            w = w[:, None]
        if w.ndim != 2 or w.shape[0] != entry.n:
            raise SlateError(
                f"Session.update: delta must be ({entry.n}, k) update "
                f"vectors, got shape {tuple(w.shape)}")
        w = np.ascontiguousarray(w, dtype=wd)
        k = int(w.shape[1])
        sign = -1 if downdate else 1
        # stage the mutated operand host-side FIRST: whatever happens
        # on the device path (abort fault, failed positivity guard),
        # A' is the committed truth every degraded path answers from
        if small:
            a_cur = np.asarray(entry.A)
            A2 = np.ascontiguousarray(
                a_cur + sign * (w @ w.conj().T), dtype=wd)
            anorm1 = float(np.linalg.norm(a_cur, 1))
        else:
            a_cur = np.asarray(
                entry.A.full_dense())[: entry.n, : entry.n]
            anorm1 = float(np.linalg.norm(a_cur, 1))
            A2 = from_dense(a_cur + sign * (w @ w.conj().T),
                            entry.A.nb, kind=entry.A.kind,
                            uplo=entry.A.uplo)
        self.metrics.inc("updates_total")
        rt = self._request_tenant_or_none(handle, tenant)
        # the fault seam fires BEFORE any resident byte is touched: an
        # injected update_abort models a mid-update failure — the
        # resident is bit-untouched and the committed operand
        # refactors (counted), the chaos exit gate
        if self.faults is not None and self._fault("update"):
            self.metrics.inc("update_aborts_total")
            self._update_commit(entry, A2)
            return self._update_refactor(entry, handle, "abort")
        res = self._cache.get(handle)
        if res is None:
            # nothing resident to maintain: commit the mutation; the
            # next factor() is a plain miss, not a counted refactor
            self._update_commit(entry, A2)
            self.metrics.inc("updates_deferred_total")
            return {"applied": False, "refactored": False,
                    "deferred": True, "info": 0, "op": entry.op,
                    "k": k}
        L = res.payload[0]
        kb = _upd.bucket_k(k)
        ldt = np.dtype(L.dtype)  # factor dtype (lo under refine)
        npad = int(L.shape[-1]) if small else int(L.mt * L.nb)
        wpad = np.zeros((npad, kb), dtype=ldt)
        wpad[: entry.n, :k] = w.astype(ldt)
        if small:
            l2, infos = _upd.chol_update_batched(
                L[None], jnp.asarray(wpad)[None], sign)
            l2 = jax.block_until_ready(l2)
            payload2 = (l2[0],)
            info = int(np.asarray(infos)[0])
        else:
            wdev = jnp.asarray(wpad)
            exe, key = self._update_exe(
                entry, handle,
                "chol_down" if downdate else "chol_up", (L, wdev))
            out, info = exe(L, wdev)
            out = jax.block_until_ready(out)
            payload2 = (out,)
            info = int(info)
            self._credit_program(key, "serve.update", tenant=rt,
                                 handle=handle)
        if downdate and info > 0:
            # the positivity guard fired: A − W·Wᴴ is not (numerically)
            # positive definite along the sweep. The incremental result
            # is discarded; the refactor of the committed operand is
            # the authority — it either succeeds (the guard was
            # rounding-conservative) or reports the indefiniteness
            # itself: detected, never served
            self.metrics.inc("update_downdate_failures_total")
            self._update_commit(entry, A2)
            return self._update_refactor(entry, handle,
                                         "downdate_indefinite")
        self._update_commit(entry, A2)
        return self._update_finish(
            entry, handle, payload2, rt, kb, k,
            float(np.linalg.norm(w, 1)) ** 2, anorm1)

    def _update_qr(self, entry: _Operator, handle: Hashable, rows,
                   delete, tenant: Optional[str]) -> dict:
        """Caller holds the lock. QR row maintenance (GGMS Q4): append
        (``rows`` = the (p, n) new rows) or delete (``delete`` = row
        indices). The resident base factors are never touched —
        appends rebuild the (w, tau, r) append block from the full
        appended stack against the resident R (O(n²·P), not O(mn²));
        deleting a BASE row has no incremental form and degrades to a
        counted refactor of the pruned operand."""
        import jax.numpy as jnp
        from ..linalg import update as _upd
        if (rows is None) == (delete is None):
            raise SlateError(
                "Session.update(qr): exactly one of delta (rows to "
                "append) or delete= (row indices) per call")
        wd = np.dtype(entry.A.dtype)
        a_cur = np.asarray(entry.A.to_dense())  # logical (m, n)
        res = self._cache.get(handle)
        base_m = res.payload[0].m if res is not None else None
        idx = None
        if rows is not None:
            u = np.asarray(rows)
            if u.ndim == 1:
                u = u[None, :]
            if u.ndim != 2 or u.shape[1] != entry.n:
                raise SlateError(
                    f"Session.update(qr): delta must be (p, {entry.n})"
                    f" rows to append, got shape {tuple(u.shape)}")
            u = np.ascontiguousarray(u, dtype=wd)
            k_live = int(u.shape[0])
            a_new = np.vstack([a_cur, u])
            m_new = entry.m + k_live
            wn1_sq = float(np.linalg.norm(u, 1)) ** 2
            base_delete = False
        else:
            idx = np.unique(np.atleast_1d(
                np.asarray(delete, dtype=np.int64)))
            if idx.size == 0:
                raise SlateError("Session.update(qr): delete= is empty")
            if int(idx[0]) < 0 or int(idx[-1]) >= entry.m:
                raise SlateError(
                    f"Session.update(qr): delete= indices out of range "
                    f"for {entry.m} rows")
            k_live = int(idx.size)
            a_new = np.delete(a_cur, idx, axis=0)
            m_new = entry.m - k_live
            if m_new < entry.n:
                raise SlateError(
                    "Session.update(qr): delete would leave an "
                    f"underdetermined operator ({m_new} rows < "
                    f"{entry.n} cols)")
            wn1_sq = float(np.linalg.norm(a_cur[idx], 1)) ** 2
            base_delete = res is None or bool((idx < base_m).any())
        A2 = from_dense(a_new, entry.A.nb)
        anorm1 = float(np.linalg.norm(a_cur, 1))
        self.metrics.inc("updates_total")
        rt = self._request_tenant_or_none(handle, tenant)
        if self.faults is not None and self._fault("update"):
            self.metrics.inc("update_aborts_total")
            self._update_commit(entry, A2, m=m_new)
            return self._update_refactor(entry, handle, "abort")
        if res is None:
            self._update_commit(entry, A2, m=m_new)
            self.metrics.inc("updates_deferred_total")
            return {"applied": False, "refactored": False,
                    "deferred": True, "info": 0, "op": "qr",
                    "k": k_live}
        if base_delete:
            # no incremental form for base-row removal: the pruned
            # operand commits and a counted refactor answers
            self._update_commit(entry, A2, m=m_new)
            return self._update_refactor(entry, handle, "base_delete")
        base = res.payload[0]
        # rows already appended on top of the base factors, recovered
        # from the resident payload itself (cols beyond n and rows
        # beyond the live count are zero padding) — survives
        # checkpoint/restore with no side table
        prev = (np.asarray(res.payload[1])[: entry.m - base.m,
                                           : entry.n]
                if len(res.payload) > 1
                else np.zeros((0, entry.n), dtype=wd))
        if rows is not None:
            u_all = np.vstack([prev.astype(wd, copy=False), u])
        else:
            u_all = np.delete(prev, idx - base.m, axis=0)
        p_all = int(u_all.shape[0])
        self._update_commit(entry, A2, m=m_new)
        if p_all == 0:
            # every appended row deleted: the resident base factors
            # alone are exactly the factorization of the pruned
            # operand — zero device work
            return self._update_finish(entry, handle, (base,), rt, 0,
                                       k_live, wn1_sq, anorm1)
        P = _upd.bucket_k(p_all)
        npad = int(base.vr.shape[1])
        ldt = np.dtype(base.vr.dtype)
        upad = np.zeros((P, npad), dtype=ldt)
        upad[:p_all, : entry.n] = u_all.astype(ldt, copy=False)
        udev = jnp.asarray(upad)
        exe, key = self._update_exe(entry, handle, "qr_append",
                                    (base, udev))
        w_, tau_, r_ = jax.block_until_ready(exe(base, udev))
        self._credit_program(key, "serve.update", tenant=rt,
                             handle=handle)
        return self._update_finish(entry, handle,
                                   (base, udev, w_, tau_, r_), rt, P,
                                   k_live, wn1_sq, anorm1)

    def update_small_batched(self, handles, deltas,
                             downdate: bool = False,
                             tenant: Optional[str] = None) -> list:
        """Grouped incremental maintenance for the many-small-problems
        engine (Kalman-filter/RLS fleets): one bucketed program
        up/downdates B chol_small residents at once, through the same
        per-(B-bucket, n, k-bucket, dtype) program cache as the
        batched solve engine, with per-item info isolation (a failed
        downdate degrades THAT item to a counted refactor; the rest
        commit). Cold handles are factored on miss first (a plain
        miss, then updated). Ranks may differ per item — zero pad
        columns are exactly inert, so the group shares one program at
        the max rank's bucket. Returns one result dict per handle."""
        import jax.numpy as jnp
        from ..linalg import update as _upd
        handles = list(handles)
        deltas = list(deltas)
        if len(handles) != len(deltas):
            raise SlateError("Session.update_small_batched: handles "
                             "and deltas length mismatch")
        if not handles:
            return []
        sign = -1 if downdate else 1
        with self._lock:
            entries = []
            for h in handles:
                e = self._ops.get(h)
                if e is None:
                    raise SlateError(f"Session: unknown handle {h!r}")
                if e.op != "chol_small":
                    raise SlateError(
                        "Session.update_small_batched: chol_small "
                        f"operators only (got {e.op!r} for {h!r})")
                entries.append(e)
            keys = {self.small_group_key(h) for h in handles}
            if len(keys) != 1:
                raise SlateError(
                    "Session.update_small_batched: one (op, n, dtype"
                    "[, refine]) group per call, got "
                    f"{sorted(map(str, keys))}")
            n = entries[0].n
            wd = np.dtype(entries[0].A.dtype)
            ws = []
            for e, d in zip(entries, deltas):
                w = np.asarray(d)
                if w.ndim == 1:
                    w = w[:, None]
                if w.ndim != 2 or w.shape[0] != n:
                    raise SlateError(
                        f"Session.update_small_batched: each delta "
                        f"must be ({n}, k) vectors, got "
                        f"{tuple(w.shape)}")
                ws.append(np.ascontiguousarray(w, dtype=wd))
            kb = _upd.bucket_k(max(w.shape[1] for w in ws))
            residents = [self.factor(h) for h in handles]
            for h, r in zip(handles, residents):
                if r.info != 0:
                    raise SlateError(
                        f"Session: operator {h!r} factorization "
                        f"failed (info={r.info})")
            a_curs = [np.asarray(e.A) for e in entries]
            a2s = [np.ascontiguousarray(
                a + sign * (w @ w.conj().T), dtype=wd)
                for a, w in zip(a_curs, ws)]
            an1s = [float(np.linalg.norm(a, 1)) for a in a_curs]
            B = len(handles)
            self.metrics.inc("updates_total", B)
            if self.faults is not None and self._fault("update"):
                self.metrics.inc("update_aborts_total", B)
                outs = []
                for h, e, a2 in zip(handles, entries, a2s):
                    self._update_commit(e, a2)
                    outs.append(self._update_refactor(e, h, "abort"))
                return outs
            ldt = np.dtype(residents[0].payload[0].dtype)
            npad = int(residents[0].payload[0].shape[-1])
            wpad = np.zeros((B, npad, kb), dtype=ldt)
            for i, w in enumerate(ws):
                wpad[i, :n, : w.shape[1]] = w.astype(ldt)
            ls = jnp.stack([r.payload[0] for r in residents])
            l2, infos = _upd.chol_update_batched(
                ls, jnp.asarray(wpad), sign, live_batch=B)
            l2 = jax.block_until_ready(l2)
            infos = np.asarray(infos)[:B]
            outs = []
            for i, (h, e) in enumerate(zip(handles, entries)):
                self._update_commit(e, a2s[i])
                if downdate and int(infos[i]) > 0:
                    self.metrics.inc("update_downdate_failures_total")
                    outs.append(self._update_refactor(
                        e, h, "downdate_indefinite"))
                    continue
                outs.append(self._update_finish(
                    e, h, (l2[i],),
                    self._request_tenant_or_none(h, tenant), kb,
                    int(ws[i].shape[1]),
                    float(np.linalg.norm(ws[i], 1)) ** 2, an1s[i]))
            return outs

    def _warm_update(self, entry: _Operator, handle: Hashable, res,
                     update_k: int, nrhs: int):
        """Caller holds the lock (warmup's round-20 arm). Compile-only
        — no program executes, nothing is maintained: chol gets both
        sweep signs at the rank bucket; qr gets the append program at
        ``bucket_k(update_k)`` PLUS the appended-payload solve for
        exactly ``update_k`` appended rows at this nrhs."""
        import jax.numpy as jnp
        from ..linalg import update as _upd
        kb = _upd.bucket_k(update_k)
        if entry.op == "chol":
            L0 = res.payload[0]
            w0 = jnp.zeros((int(L0.mt * L0.nb), kb), dtype=L0.dtype)
            self._update_exe(entry, handle, "chol_up", (L0, w0))
            self._update_exe(entry, handle, "chol_down", (L0, w0))
            return
        base = res.payload[0]
        npad = int(base.vr.shape[1])
        dt = base.vr.dtype
        u0 = jnp.zeros((kb, npad), dtype=dt)
        self._update_exe(entry, handle, "qr_append", (base, u0))
        pay5 = (base, u0, jnp.zeros((kb, npad), dtype=dt),
                jnp.zeros((npad,), dtype=dt),
                jnp.zeros((npad, npad), dtype=dt))
        B = self._wrap_rhs(entry, np.zeros(
            (entry.m + int(update_k), nrhs), np.dtype(entry.A.dtype)))
        skey = self._aot_key(entry, pay5, B)
        if skey not in self._compiled:
            fn = self._solve_fn(entry)
            self._compiled_put(
                skey, self._aot_compile("solve", entry, handle, fn,
                                        (pay5, B), key=skey))
            self.metrics.inc("aot_compiles")

    def _update_exe(self, entry: _Operator, handle: Hashable,
                    kind: str, args: Tuple):
        """AOT executable for one maintenance program — the _probe_exe
        discipline: cached per (kind, op, opts, treedef, shapes) so a
        k-bucketed update stream pays O(log k) compiles (counted in
        ``aot_compiles``/``update_aot_compiles``), every program
        analyzed so executions credit the bytes ledger and the budget
        sees the transient. Returns ``(exe, key)``."""
        leaves, treedef = jax.tree_util.tree_flatten(args)
        shapes = tuple((tuple(l.shape), str(l.dtype)) for l in leaves)
        key = ("update", kind, entry.op, entry.opts, treedef, shapes)
        exe = self._compiled.get(key)
        if exe is None:
            from ..linalg import update as _upd
            opts = entry.opts
            if kind == "qr_append":
                def make():
                    return lambda qr, u: _upd.qr_append_factor(qr, u)
            else:
                sign = 1 if kind == "chol_up" else -1

                def make():
                    return lambda L, w: _upd.chol_update_factor(
                        L, w, sign, opts)
            fn = self._jit_cached(("update", kind, entry.op,
                                   entry.opts), make)
            exe = self._aot_compile("update", entry, handle, fn, args,
                                    key=key)
            self._compiled_put(key, exe)
            self.metrics.inc("aot_compiles")
            self.metrics.inc("update_aot_compiles")
        else:
            self._compiled.move_to_end(key)
        return exe, key

    def _update_commit(self, entry: _Operator, A2,
                       m: Optional[int] = None):
        """Caller holds the lock: the mutated operand becomes the
        operator's truth. Cached norms are stale — dropped, refreshed
        lazily by the next refined solve / condest probe."""
        entry.A = A2
        if m is not None:
            entry.m = m
        entry.anorm = None
        entry.anorm1 = None

    def _update_evict(self, handle: Hashable):
        """Caller holds the lock: drop the resident (counted eviction,
        residency interval closed) ahead of a degrade-to-refactor."""
        res = self._cache.pop(handle, None)
        if res is None:
            return
        self.metrics.inc("evictions")
        self.metrics.inc("evicted_bytes", res.nbytes)
        if self.attribution is not None:
            self._attr_evicted(handle)
        rec = self.recorder
        if rec is not None:
            self._journal_evict(rec, handle, res.nbytes, "update")
        self._update_hbm_gauges()

    def _update_refactor(self, entry: _Operator, handle: Hashable,
                         reason: str, applied: bool = False) -> dict:
        """Caller holds the lock, mutated operand committed. The
        counted degrade path every update failure funnels through:
        evict the (stale or discarded) resident and refactor A' —
        which either serves correctly or reports its own info, never
        a wrong answer from a half-maintained factor."""
        self.metrics.inc("update_refactors_total")
        rec = self.recorder
        if rec is not None:
            # outcome carries the degrade reason; reason "budget" is
            # the OUTCOME_COUNTERS slice that mirrors
            # update_budget_refactors_total (one decision, two counters)
            rec.decision("update_refactor", op=entry.op, handle=handle,
                         tenant=entry.tenant, outcome=reason,
                         inputs={"applied": applied})
        self._update_evict(handle)
        res = self.factor(handle)
        return {"applied": applied, "refactored": True,
                "reason": reason, "info": int(res.info),
                "op": entry.op}

    def _update_finish(self, entry: _Operator, handle: Hashable,
                       payload2: Tuple, rt: Optional[str], kb: int,
                       k: int, wnorm1_sq: float,
                       anorm1: float) -> dict:
        """Caller holds the lock, operand committed. Install the
        maintained resident, credit the executed-bucket update flops
        (counters + process ledger + attribution cell, all
        grid-snapped — the conservation discipline), then run the
        numerics accrual: if the accumulated update error mass crosses
        the budget, the just-served resident refactors NOW (counted),
        off the next request's path."""
        res2 = _Resident(payload2, 0,
                         _tree_nbytes(payload2, per_chip=True),
                         _tree_nbytes(payload2))
        self._cache[handle] = res2
        self._cache.move_to_end(handle)
        fl = 0.0
        if kb:
            fl = _fl_grid(_flops_mod.update_flops(
                entry.op, entry.m, entry.n, kb))
            self.metrics.inc("flops_total", fl)
            self.metrics.inc("update_flops_total", fl)
            _LEDGER.record("serve.update", fl)
        attr = self.attribution
        if attr is not None:
            if fl:
                attr.record("update_flops", rt, handle, fl)
            inc = attr.touch_residency(entry.tenant, handle,
                                       res2.nbytes)
            if inc:
                self.metrics.inc("residency_byte_seconds_total", inc)
        self._update_hbm_gauges()
        self._evict_to_budget(keep=handle)
        if self.tenant_policies is not None:
            self._evict_tenant_to_budget(entry.tenant, keep=handle)
        refactored = self._update_health(entry, handle, k, wnorm1_sq,
                                         anorm1)
        out = {"applied": True, "refactored": bool(refactored),
               "info": 0, "op": entry.op, "k": k, "k_bucket": kb}
        if refactored:
            out["reason"] = "update_budget"
        return out

    def _update_health(self, entry: _Operator, handle: Hashable,
                       k: int, wnorm1_sq: float,
                       anorm1: float) -> bool:
        """Caller holds the lock, maintained resident installed.
        Accrue the update's growth-weighted error mass and consult the
        refactor-due predicate (obs/numerics.py — ONE source of truth:
        the monitor keeps the authoritative per-handle copy when
        attached, the operator entry carries the monitor-less
        fallback). Returns True when the budget came due and a counted
        refactor replaced the accumulated-error resident."""
        weight = _num.update_weight(k, wnorm1_sq, anorm1)
        nm = self.numerics
        if nm is not None:
            old, new = nm.record_update(handle, k, weight)
            self._health_reflex(entry, handle, old, new)
            due = nm.update_due(handle)
        else:
            entry.updates += 1
            entry.update_weight += weight
            due = _num.update_refactor_due(entry.updates,
                                           entry.update_weight,
                                           _num.DEFAULT_UPDATE_BUDGET)
        if not due:
            return False
        self.metrics.inc("update_budget_refactors_total")
        self._update_refactor(entry, handle, "budget", applied=True)
        return True

    @staticmethod
    def _aot_key(entry: _Operator, payload, B) -> Hashable:
        leaves, treedef = jax.tree_util.tree_flatten((payload, B))
        shapes = tuple((tuple(l.shape), str(l.dtype)) for l in leaves)
        return (entry.op, entry.opts, treedef, shapes)

    # -- AOT warmup --------------------------------------------------------

    def warmup(self, handle: Hashable, nrhs: int = 1,
               update_k: Optional[int] = None):
        """Ahead-of-time path: AOT-compile the whole-factor program
        (dense operators; the lookahead-pipeline driver — round 7),
        factor ``handle`` through it now (off the request path), and
        ``jit(...).lower(...).compile()`` the solve for an
        (rows, nrhs) right-hand side, caching the executables so
        request-time refactors AND solves skip tracing and
        compilation. Dense right-hand sides are tile-padded, so one
        warmup at nrhs=1 covers every bucket width up to the
        operator's nb.

        ``update_k`` (round 20): additionally precompile the
        incremental-maintenance programs at ``bucket_k(update_k)`` —
        both chol sweep signs (zero update vectors are exactly inert,
        so one warm covers every live rank in the bucket), or the QR
        append program plus the appended-payload solve for EXACTLY
        ``update_k`` appended rows (the appended solve's rhs height is
        m + p, so each append count is its own program). After this, a
        served update at the bucket is zero new compiles (the
        acceptance pin)."""
        with self._lock:
            entry = self._ops.get(handle)
            if entry is None:
                raise SlateError(f"Session: unknown handle {handle!r}")
            if entry.op in SMALL_OPS:
                # small ops compile through linalg/batched's own
                # per-bucket program cache: factor now (real work — the
                # cached factor serves requests, so it IS credited) and
                # run one zero-rhs solve so the B=1 solve bucket program
                # exists before the first request; the probe solve is
                # fake traffic and its ledger crediting is suppressed
                from ..linalg import batched as _batched
                res = self.factor(handle)
                if res.info == 0:
                    b0 = np.zeros((entry.n, nrhs),
                                  dtype=np.dtype(entry.A.dtype))
                    with _batched.suppress_accounting():
                        if entry.refine is not None:
                            a0 = np.asarray(entry.A)
                            pol = entry.refine
                            if entry.op == "lu_small":
                                lu, perm = res.payload
                                _batched.getrs_refined_batched(
                                    a0[None], lu[None], perm[None],
                                    b0[None], max_iters=pol.max_iters,
                                    tol=pol.tol)
                            else:
                                _batched.potrs_refined_batched(
                                    a0[None], res.payload[0][None],
                                    b0[None], max_iters=pol.max_iters,
                                    tol=pol.tol)
                        elif entry.op == "lu_small":
                            lu, perm = res.payload
                            _batched.getrs_batched(lu[None], perm[None],
                                                   b0[None])
                        else:
                            _batched.potrs_batched(res.payload[0][None],
                                                   b0[None])
                if (update_k is not None and res.info == 0
                        and entry.op == "chol_small"):
                    # populate the batched sweep's bucket programs at
                    # this rank bucket (zero W is exactly inert, so
                    # running it maintains nothing); suppressed — fake
                    # traffic credits no bytes
                    import jax.numpy as jnp
                    from ..linalg import update as _upd
                    kb = _upd.bucket_k(update_k)
                    L0 = res.payload[0]
                    w0 = jnp.zeros((1, int(L0.shape[-1]), kb),
                                   dtype=L0.dtype)
                    with _batched.suppress_accounting():
                        _upd.chol_update_batched(L0[None], w0, 1)
                        _upd.chol_update_batched(L0[None], w0, -1)
                return
            if entry.op in SPECTRAL_OPS:
                # round 19: factoring runs every pipeline stage through
                # the _aot_compile seam (the stage hook in
                # _factor_spectral), so the factor call below IS the
                # stage warmup; then AOT-compile the served apply for
                # EVERY catalog function at this rhs width (θ is a
                # traced scalar — warmed once, any value serves), plus
                # the sampled residual-probe program when the numerics
                # monitor is on. After this, a served apply is zero
                # new compiles (the acceptance pin).
                from .. import spectral as _spectral
                res = self.factor(handle)
                catalog = _spectral.function_catalog(entry.op)
                wd = np.dtype(entry.A.dtype)
                for fname, (_wf, forward) in catalog.items():
                    rows = (entry.n if entry.op == "eig"
                            else (entry.n if forward else entry.m))
                    B = self._wrap_rhs(entry,
                                       np.zeros((rows, nrhs), wd))
                    self._spectral_apply_exe(
                        entry, handle, fname,
                        (res.payload, B,
                         self._spectral_theta(entry, 0.0)))
                if self.numerics is not None:
                    args = (res.payload, entry.A)
                    leaves, treedef = jax.tree_util.tree_flatten(args)
                    shapes = tuple((tuple(l.shape), str(l.dtype))
                                   for l in leaves)
                    pkey = ("spectral.probe", entry.op, entry.opts,
                            treedef, shapes)
                    if pkey not in self._compiled:
                        fn = self._jit_cached(
                            ("spectral.probe", entry.op, entry.opts),
                            lambda: _spectral.make_probe_fn(
                                entry.op, entry.opts))
                        self._compiled_put(
                            pkey, self._aot_compile(
                                "probe", entry, handle, fn, args,
                                key=pkey))
                        self.metrics.inc("aot_compiles")
                return
            if entry.op in ("lu", "chol", "qr"):
                fkey = self._factor_key(entry)
                if fkey not in self._compiled:
                    ffn = self._factor_fn(entry)
                    self._compiled_put(
                        fkey, self._aot_compile(
                            "factor", entry, handle, ffn, (entry.A,),
                            key=fkey))
                    self.metrics.inc("factor_aot_compiles")
            res = self.factor(handle)
            if (update_k is not None and res.info == 0
                    and entry.op in ("chol", "qr")
                    and entry.grid is None):
                self._warm_update(entry, handle, res, update_k, nrhs)
            B = self._wrap_rhs(
                entry, np.zeros((entry.m, nrhs)))
            if entry.refine is not None:
                if entry.refine.strategy == "gmres":
                    # the GMRES-IR cycle jit-caches itself
                    # (linalg/gmres._fgmres_cycle); factoring above was
                    # the warmup
                    return
                # refined entries serve through the start/step
                # programs: compile both off the request path (the
                # start's probe output supplies the step's X shapes;
                # its execution credits nothing — only the explicit
                # _credit_program calls on the serving path do)
                start_exe, _ = self._refine_exe(entry, handle, "start",
                                                (res.payload, B))
                X0 = start_exe(res.payload, B)
                self._refine_exe(entry, handle, "step",
                                 (res.payload, entry.A, B, X0))
                if self.numerics is not None and entry.op == "lu":
                    # the condest conjugate-transpose program at the
                    # (n, 1) probe shape (nrhs=1 warmup covers it) —
                    # so a warmed refined LU's condest adds no
                    # request-path compiles
                    self._condest_texe(entry, handle, res.payload, B)
                return
            key = self._aot_key(entry, res.payload, B)
            if key not in self._compiled:
                fn = self._solve_fn(entry)
                self._compiled_put(
                    key, self._aot_compile("solve", entry, handle, fn,
                                           (res.payload, B), key=key))
                self.metrics.inc("aot_compiles")
            if self.numerics is not None:
                # round 16: precompile the numerics programs off the
                # request path — the fused solve+residual probe at
                # THIS nrhs (the probe's fused norms read the logical
                # width, so other widths compile, counted, on first
                # probed use) and LU's condest transpose solve.
                # Condest's forward applies reuse the solve executable
                # compiled above (same shapes), so a warmed operator's
                # condest adds ZERO compiles (mesh acceptance pin).
                if entry.op in PROBE_OPS:
                    self._probe_exe(entry, handle,
                                    (res.payload, entry.A, B))
                if entry.op == "lu":
                    self._condest_texe(entry, handle, res.payload, B)

    def _aot_compile(self, what: str, entry: _Operator, handle: Hashable,
                     fn, args: Tuple, key: Optional[Hashable] = None):
        """``jit(...).lower(...).compile()`` with compile-time
        observability: the trace+lower and compile stages are timed
        separately into ``warmup_lower_latency`` /
        ``warmup_compile_latency`` histograms and appended per shape to
        ``Session.compile_log`` — the numbers a serving fleet needs to
        budget warmup and alarm on recompiles.

        Round 9: the same seam harvests XLA's cost/memory analyses
        (obs/costs.py) into ``Session.cost_log`` — per shape: model
        flops, bytes-accessed, argument/output/temp/peak HBM, and the
        collective census — and keeps the ProgramCosts keyed under the
        executable's cache key so every execution credits the bytes
        ledger and the budget accounts the program's transient HBM."""
        if self.faults is not None:
            self._fault("compile")  # compile_stall: injected latency
        with self.metrics.phase("serve.warmup", tracer=self.tracer,
                                stage=what,
                                **self._span_attrs(entry, handle)):
            t0 = time.perf_counter()
            lowered = fn.lower(*args)
            t1 = time.perf_counter()
            exe = lowered.compile()
            t2 = time.perf_counter()
        self.metrics.observe("warmup_lower_latency", t1 - t0)
        self.metrics.observe("warmup_compile_latency", t2 - t1)
        leaves = jax.tree_util.tree_leaves(args)
        shapes = [tuple(getattr(l, "shape", ())) for l in leaves]
        self.compile_log.append({
            "op": entry.op, "what": what, "shape": shapes,
            "lower_s": t1 - t0, "compile_s": t2 - t1,
        })
        pc = _costs.program_costs(exe)
        if key is not None:
            self._program_costs[key] = pc
        # rhs width of the program (last array arg; the spectral apply
        # carries a trailing scalar θ, so its rhs is one slot earlier)
        wshape = (shapes[-2] if what == "apply" and len(shapes) >= 2
                  else shapes[-1] if shapes else ())
        kk = wshape[1] if len(wshape) > 1 else 1
        if what == "factor":
            model_fl = _factor_flops(entry.op, entry.m, entry.n,
                                     entry.band)
        elif what.startswith("spectral."):
            # one staged spectral program: the stage's own dominant
            # term (obs/flops.py SPECTRAL_STAGE_MODELS), snapped to
            # the counter grid like every other model numerator
            model_fl = _fl_grid(_flops_mod.spectral_stage_flops(
                what, entry.m, entry.n,
                getattr(entry.A, "nb", entry.band) or 1))
        elif what == "refine_step":
            # one refinement step: the working-precision residual gemm
            # plus one low-precision factor apply
            model_fl = (_flops_mod.gemm(entry.n, kk, entry.n)
                        + _solve_flops(entry.op, entry.m, entry.n, kk,
                                       entry.band))
        elif what == "update":
            # round 20: one incremental-maintenance program. The rank
            # operand is the LAST arg — (npad, kb) vectors for chol
            # (rank = cols), (P, npad) appended rows for qr (rank =
            # rows) — and the model charges the executed bucket
            model_fl = _fl_grid(_flops_mod.update_flops(
                entry.op, entry.m, entry.n,
                (wshape[0] if entry.op == "qr" else kk)
                if wshape else 1))
        else:
            model_fl = _solve_flops(entry.op, entry.m, entry.n, kk,
                                    entry.band)
        self.cost_log.append({
            "op": entry.op, "what": what, "shape": shapes,
            "model_flops": model_fl, "tuned_config": entry.tuned,
            **pc.to_dict(),
        })
        self._cost_index[(entry.op, what)] = float(model_fl or 0.0)
        self._update_hbm_gauges()
        return exe

    # -- placement snapshot (round 15: the fleet-fold placement input) -----

    def placement_snapshot(self, host: Optional[str] = None) -> dict:
        """One schema-validated row per RESIDENT factor — {host,
        tenant, handle, op, n, dtype, bytes_per_chip, heat,
        last_access} — the per-process half of the fleet placement
        input (``obs.aggregate.merge_placement_snapshots`` folds N of
        these into the row set ROADMAP item 1's cache tier and quota
        scheduler consume). ``bytes_per_chip`` is the resident's
        PER-CHIP budget charge (max-per-shard for mesh residents — the
        round-11 convention); heat/last_access come from the
        attribution ledger (0.0/null without one). The producer
        validates its own output against the committed schema
        (obs.attribution.validate_placement_snapshot) so a drifted row
        shape fails HERE, not in a consumer three hops away."""
        if host is None:
            import os as _os
            import socket as _socket
            host = f"{_socket.gethostname()}:{_os.getpid()}"
        attr = self.attribution
        # LOCK-FREE on purpose (the op_meta/small_group_key
        # discipline): the session lock is held across whole device
        # executions, and a /tenants scrape must not stall behind an
        # in-flight solve. list(dict.items()) is one GIL-atomic C
        # call, _Resident/_Operator fields are immutable after
        # insert, and a raced unregister just skips its row — a
        # scrape reads the cache as of one instant, which is all a
        # snapshot ever promises.
        if attr is not None:
            # bring residency byte-seconds current so the snapshot
            # and the counters describe the same instant (the ledger
            # has its own lock)
            inc = attr.accrue_residency()
            if inc:
                self.metrics.inc("residency_byte_seconds_total", inc)
        heat_rows = attr.heat_rows() if attr is not None else {}
        nm = self.numerics
        rows = []
        for h, res in list(self._cache.items()):
            entry = self._ops.get(h)
            if entry is None:
                continue  # unregister raced the scrape
            A = entry.A
            dtype = (A.ab.dtype if isinstance(A, PackedBand)
                     else A.dtype)
            hr = repr(h)
            heat, last = heat_rows.get(hr, (0.0, None))
            # round-16 health columns: a placement policy must see
            # what the numerics monitor sees (a hot-but-suspect
            # resident is a replication candidate NOBODY should copy);
            # null without a monitor — the disabled-path row shape
            health, ce, gr = (nm.placement_info(h) if nm is not None
                              else (None, None, None))
            rows.append({
                "host": host,
                "tenant": self.request_tenant(h),
                "handle": hr,
                "op": entry.op,
                "n": int(entry.n),
                "dtype": str(dtype),
                "bytes_per_chip": int(res.nbytes),
                "heat": heat,
                "last_access": last,
                "health": health,
                "condest": ce,
                "growth": gr,
            })
        doc = {
            "schema": PLACEMENT_SCHEMA,
            "host": host,
            "generated_at": time.time(),
            "rows": rows,
        }
        errs = validate_placement_snapshot(doc)
        if errs:
            raise SlateError(
                f"Session.placement_snapshot: schema self-check failed "
                f"({errs[:3]})")
        return doc

    def tenants_payload(self) -> dict:
        """The ``/tenants`` route payload: the attribution ledger's
        per-(tenant, handle) cells + tenant/global totals (residency
        accrued to now via the placement pass) and the placement
        snapshot. ``{"enabled": false}`` without a ledger."""
        if self.attribution is None:
            return {"enabled": False, "tenants": {},
                    "quotas": self.quotas_payload()}
        placement = self.placement_snapshot()  # accrues residency
        payload = self.attribution.snapshot()
        payload["enabled"] = True
        payload["placement"] = placement
        # round 18: the quota view rides the same route (policies,
        # per-tenant resident bytes vs sub-budget, quota counters)
        payload["quotas"] = self.quotas_payload()
        return payload

    def numerics_payload(self) -> dict:
        """The ``/numerics`` route payload: the monitor's per-handle
        signal rows + state histogram + config, plus the session's
        probe counters. ``{"enabled": false}`` without a monitor."""
        if self.numerics is None:
            return {"enabled": False, "handles": {}}
        payload = self.numerics.snapshot()
        payload["enabled"] = True
        payload["counters"] = {k: self.metrics.get(k) for k in (
            "condest_runs_total", "condest_solves_total",
            "residual_probes_total", "numerics_flops_total",
            "numerics_nonfinite_total", "health_transitions_total",
            "health_demotions_total", "refine_demotions_total")}
        return payload

    # -- checkpoint/restore (round 17: runtime/checkpoint.py) --------------

    def checkpoint(self, path: str, only: Optional[List[Hashable]] = None,
                   host: Optional[str] = None) -> dict:
        """Write this session's RESIDENT state (factor trees + full
        operator metadata, per-blob checksums) to checkpoint directory
        ``path`` — the durable artifact :meth:`restore` warm-restarts
        from without refactoring. ``only`` filters to a handle subset
        (the fleet's replication transfer). Returns the manifest
        (schema ``slate_tpu.checkpoint.v1``, producer-validated)."""
        from .checkpoint import save_session
        return save_session(self, path, only=only, host=host)

    def restore(self, path: str,
                only: Optional[List[Hashable]] = None,
                manifest: Optional[dict] = None) -> dict:
        """Warm-restart from a checkpoint directory: re-register each
        record's operator and re-insert its factor WITHOUT refactoring
        — a restored handle's solve is bit-identical to the
        pre-checkpoint resident's (dense/small/refined entries; mesh
        residents re-shard onto the current grid, round-11 rule).
        Heat/health/tenant carry over when the matching obs components
        are attached. A payload whose checksum fails degrades to
        refactor-on-miss, counted in ``restore_corrupt_total`` — never
        a wrong answer. Returns the restore summary. ``manifest``: an
        already-loaded manifest for ``path`` (skips the re-parse — the
        fleet's per-handle failover restores)."""
        from .checkpoint import restore_session
        return restore_session(self, path, only=only, manifest=manifest)

    def close(self):
        """Orderly shutdown: when a ``checkpoint_dir`` is configured,
        flush a final checkpoint plus a placement snapshot there (the
        state a fleet failover needs to recover this process's
        residents — before round 17, close dropped both on the floor),
        then stop the observability endpoint. Idempotent."""
        if self.checkpoint_dir is not None:
            import json as _json
            os.makedirs(self.checkpoint_dir, exist_ok=True)
            self.checkpoint(os.path.join(self.checkpoint_dir,
                                         "checkpoint"))
            doc = self.placement_snapshot()
            tmp = os.path.join(self.checkpoint_dir, "placement.json.tmp")
            with open(tmp, "w") as f:
                _json.dump(doc, f, indent=2, sort_keys=True)
                f.write("\n")
            os.replace(tmp, os.path.join(self.checkpoint_dir,
                                         "placement.json"))
        self.close_obs()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- observability endpoint --------------------------------------------

    def serve_obs(self, host: str = "127.0.0.1", port: int = 0):
        """Opt-in observability HTTP endpoint for THIS session
        (stdlib-only): /metrics (Prometheus text, plus the tenant_*
        sections once ``enable_attribution`` ran), /healthz,
        /trace.json (Chrome trace of the session's tracer), /slo
        (burn-rate payload once ``enable_slo`` ran), /tenants (the
        attribution + placement payload) — every provider is a
        getter, so enabling AFTER serve_obs still works. Returns
        the ObsServer (``.url()`` gives the scrape target); idempotent
        — a second call returns the running server."""
        with self._lock:
            if self._obs_server is None:
                from ..obs.exposition import ObsServer
                self._obs_server = ObsServer(
                    self.metrics, tracer=self.tracer,
                    host=host, port=port,
                    slo=lambda: self.slo,
                    tenants=lambda: self.tenants_payload(),
                    attribution=lambda: self.attribution,
                    numerics=lambda: self.numerics_payload(),
                    quotas=lambda: self.quotas_payload(),
                    recorder=lambda: self.recorder,
                    history=lambda: self.timeseries,
                    forecast=lambda: self.forecaster)
            return self._obs_server

    def close_obs(self):
        """Shut down the observability endpoint, if started."""
        with self._lock:
            srv, self._obs_server = self._obs_server, None
        if srv is not None:
            srv.close()


def _make_factor_fn(op: str, opts: Options):
    """The dense factor verb as an A -> (payload, info) function — one
    whole-program jit per (op, opts). opts carries the round-7
    ``lookahead`` pipeline flag into the compiled driver."""
    import jax.numpy as jnp

    if op == "lu":
        def factor(A):
            LU, perm, info = api.lu_factor(A, opts)
            return (LU, perm), info
    elif op == "chol":
        def factor(A):
            L, info = api.chol_factor(A, opts)
            return (L,), info
    else:
        def factor(A):
            return (api.qr_factor(A, opts),), jnp.zeros((), jnp.int32)
    factor.__name__ = f"serve_{op}_factor"
    return factor


def _make_probe_fn(op: str, opts: Options):
    """The fused solve+residual program (round 16): the op's
    *_solve_using_factor verb PLUS one residual gemm (``api.multiply``
    — hemm for Hermitian operands, gemm otherwise; under GSPMD a
    sharded A partitions it with its collectives, so mesh probes stay
    sharded end to end) and the stacked (‖b−Ax‖_max, ‖x‖_max,
    ‖b‖_max) triple — so the host convergence read costs the one sync
    the solve already pays (the refine-engine norm discipline)."""
    import jax.numpy as jnp
    solve = _make_solve_fn(op, opts)

    def probe(payload, A, B):
        X = solve(payload, B)
        R = api.multiply(-1.0, A, X, 1.0, B, opts)
        stats = jnp.stack([
            jnp.max(jnp.abs(R.dense_canonical())),
            jnp.max(jnp.abs(X.dense_canonical())),
            jnp.max(jnp.abs(B.dense_canonical())),
        ])
        return X, stats

    probe.__name__ = f"serve_{op}_probe"
    return probe


def _make_solve_fn(op: str, opts: Options):
    """The *_solve_using_factor verb as a (payload, B) -> X function —
    one jit per op kind; jax's cache keys the rest off shapes/treedefs."""
    if op in ("lu", "band_lu"):
        def solve(payload, B):
            LU, perm = payload
            return api.lu_solve_using_factor(LU, perm, B, opts)
    elif op in ("chol", "band_chol"):
        def solve(payload, B):
            return api.chol_solve_using_factor(payload[0], B, opts)
    else:
        def solve(payload, B):
            if len(payload) > 1:
                # round 20: an appended-rows QR resident carries the
                # 5-tuple (base, u, w, tau, r) — python-level arity
                # branch: jit keys on the treedef, so each payload
                # shape traces its own program, never a mixed one
                from ..linalg import update as _upd
                return _upd.appended_gels(payload, B, opts)
            return api.least_squares_solve_using_factor(payload[0], B, opts)
    solve.__name__ = f"serve_{op}_solve"
    return solve


# -- process-wide session shared with the C API ----------------------------

_DEFAULT: Optional[Session] = None
_DEFAULT_LOCK = threading.Lock()

# resident-factor budget for the shared session: without a bound, every
# handle a long-lived native caller ever solves against would pin its
# factor in HBM forever. 4 GiB default (a quarter of a v5e chip's HBM),
# overridable in bytes via the env var.
_DEFAULT_BUDGET_ENV = "SLATE_TPU_SERVE_HBM_BUDGET"
_DEFAULT_BUDGET = 4 << 30


def default_session() -> Session:
    """The process-wide Session. The C-API opaque-handle solve verbs
    (compat/c_glue.py) and in-process Python callers share this one
    instance, so a factorization paid by either side serves both. Its
    factor cache is bounded (see _DEFAULT_BUDGET / the
    SLATE_TPU_SERVE_HBM_BUDGET env var)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            import os
            budget = int(os.environ.get(_DEFAULT_BUDGET_ENV,
                                        _DEFAULT_BUDGET))
            _DEFAULT = Session(hbm_budget=budget)
        return _DEFAULT
