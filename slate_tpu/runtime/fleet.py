"""Fleet failover coordinator: N Session processes, one serving surface.

ROADMAP item 1's *reflex* half. Rounds 12–16 gave the fleet its senses
(placement snapshots, handle heat, numerical health, SLO burn rates);
this module is the coordinator that ACTS on them so a process death
costs bounded unavailability and zero wrong answers — the serving
answer to the reference's MPI abort-on-failure model (a lost rank
kills a SLATE job; a lost Session process here loses one replica):

* **Consistent-hash placement**: every handle lands on a member chosen
  by a blake2b hash ring (virtual nodes for balance) — deterministic,
  so any coordinator instance derives the same placement from the same
  member set, and a member's death moves only ITS handles (the
  classic consistent-hashing property). The fleet retains each
  registration's operand spec: re-registering on a survivor is always
  possible (counted refactor-on-miss — the recovery floor).
* **Heat-driven replication**: :meth:`replicate_hot` reads the merged
  round-15 placement snapshot (``merge_placement_snapshots`` of every
  member's ``placement_snapshot()`` rows — heat-sorted), and
  replicates the top-K hottest handles onto their next ring member via
  a **checkpoint transfer** (runtime/checkpoint.py), so the replica's
  resident factor is byte-identical to the primary's, heat and health
  included.
* **Migration-on-eviction** (round 18): :meth:`migrate_pressured`
  moves an HBM-pressured member's COLDEST residents (heat rows
  ascending — the inverse of :meth:`replicate_hot`) to the
  least-loaded member via the same checkpoint-transfer path, instead
  of evicting them into refactor-on-miss: byte-identical resident on
  arrival, routed requests follow the move (queued source requests
  drain against the still-resident factor first — zero lost
  futures), 0 refactors vs 1/handle for plain eviction. A seeded
  ``migration_abort`` kills a transfer attempt mid-flight: the source
  keeps serving untouched and the coordinator retries once, counted
  — never a half-resident on the target.
* **Failover**: :meth:`kill` declares a process death. Its queued
  (in-flight) requests re-route to survivors (counted — zero lost
  futures); its handles walk the recovery ladder: a surviving replica
  serves IMMEDIATELY with no refactor → else the dead member's last
  checkpoint restores a warm resident onto the next ring member → else
  the retained spec re-registers cold (counted refactor-on-miss). A
  ``replica_stale`` fault (or real staleness) refreshes instead of
  serving stale bits; a corrupt checkpoint record is caught by its
  checksum and degrades to refactor — never a wrong answer. The
  round-14 :class:`~.batching.ShedPolicy` rides every member's
  Batcher, so the recovery surge is admission-controlled on the
  survivors instead of melting them.

The coordinator owns **no threads**: members are driven by
:meth:`pump`/:meth:`flush` on the caller's thread (the chaos-drill
determinism discipline — ``tools/chaos_serve.py`` exit-gates same-seed
schedule reproducibility across the crash). An Executor-fronted fleet
is composable later; the failover logic is thread-agnostic.
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib
import os
import shutil
import tempfile
import threading
from collections import defaultdict
from concurrent.futures import Future, InvalidStateError
from typing import Dict, Hashable, List, Optional

from ..core.exceptions import SlateError
from ..obs.tracing import log as _obs_log
from .batching import Batcher, ShedPolicy
from .checkpoint import MANIFEST_NAME
from .metrics import Metrics
from .session import Session


def _hval(s: str) -> int:
    """Deterministic 64-bit ring position (blake2b — the faults.py
    keyed-hash discipline: stable across processes and runs)."""
    return int.from_bytes(hashlib.blake2b(s.encode(),
                                          digest_size=8).digest(), "big")


@dataclasses.dataclass
class _Member:
    name: str
    session: Session
    batcher: Batcher
    alive: bool = True
    # newest checkpoint directory checkpoint_all() flushed for this
    # member; _checkpoint_of falls back to the derivable
    # <base>/checkpoint path (Session.close's flush, or a prior
    # coordinator's) when this is unset — what failover restores from
    checkpoint_path: Optional[str] = None


@dataclasses.dataclass
class _Spec:
    """Retained registration spec: operands are client-supplied and
    durable (the control plane can always re-supply them); FACTORS are
    the expensive state checkpoints protect. Re-registering this spec
    on a survivor is the recovery floor — counted refactor-on-miss."""

    A: object
    op: str
    kwargs: dict


class _FleetRequest:
    __slots__ = ("handle", "b", "kwargs", "future", "member", "mfut")

    def __init__(self, handle, b, kwargs):
        self.handle = handle
        self.b = b
        self.kwargs = kwargs
        self.future = Future()
        self.member: Optional[str] = None
        self.mfut: Optional[Future] = None


class Fleet:
    """Coordinator over named Session members (module docstring).

    ``sessions``: ``{name: Session}``. ``checkpoint_root``: per-member
    checkpoint directories default to ``<root>/<name>`` for members
    whose Session has no ``checkpoint_dir`` of its own. ``shed_policy``
    rides every member's Batcher (admission control + load shedding —
    the survivors' protection during a recovery surge). ``faults``: a
    :class:`~.faults.FaultInjector` consulted at the fleet seams
    (``fleet.process`` is fired by the chaos driver; ``fleet.replica``
    here per replica-served failover handle)."""

    def __init__(self, sessions: Dict[str, Session], *,
                 max_batch: int = 8, max_wait: float = 3600.0,
                 shed_policy: Optional[ShedPolicy] = None,
                 checkpoint_root: Optional[str] = None,
                 vnodes: int = 16, faults=None,
                 metrics: Optional[Metrics] = None, recorder=None):
        if not sessions:
            raise SlateError("Fleet: at least one member session")
        self.metrics = metrics or Metrics()
        self.faults = faults
        # decision journal (obs/recorder.py): coordinator reflexes
        # (failover rungs, migration, sync choice) are decisions too;
        # None = one is-None check per seam (round-8 discipline)
        self.recorder = recorder
        self.checkpoint_root = checkpoint_root
        self._members: Dict[str, _Member] = {}
        for name, sess in sessions.items():
            self._members[str(name)] = _Member(
                str(name), sess,
                Batcher(sess, max_batch=max_batch, max_wait=max_wait,
                        shed_policy=shed_policy))
        ring = []
        for name in self._members:
            for v in range(vnodes):
                ring.append((_hval(f"{name}#{v}"), name))
        ring.sort()
        self._ring_keys = [k for k, _ in ring]
        self._ring_names = [n for _, n in ring]
        self._lock = threading.RLock()
        self._specs: Dict[Hashable, _Spec] = {}
        # handle -> member names currently REGISTERED to serve it
        # (placement[0] is the routing preference; replicas follow)
        self._placement: Dict[Hashable, List[str]] = {}
        self._by_repr: Dict[str, Hashable] = {}
        self._inflight: Dict[str, List[_FleetRequest]] = defaultdict(list)
        self._seq = 0
        # (handle, replica member) -> (retained base checkpoint dir,
        # its manifest): what round-20 delta syncs diff against. The
        # retained dir is refreshed after every successful sync so the
        # next delta ships only the NEWEST update's changed blobs.
        self._replica_base: Dict[tuple, tuple] = {}
        self._xfer_root: Optional[str] = None
        self.metrics.set_gauge("fleet_alive_members",
                               len(self._members))

    # -- placement ----------------------------------------------------------

    def ring_order(self, handle: Hashable) -> List[str]:
        """Member names in consistent-hash preference order for one
        handle: walk the ring clockwise from the handle's position,
        collecting distinct members. Pure function of (member set,
        handle) — every coordinator derives the same answer."""
        start = bisect.bisect_left(self._ring_keys,
                                   _hval(repr(handle)))
        order, seen = [], set()
        n = len(self._ring_names)
        for i in range(n):
            name = self._ring_names[(start + i) % n]
            if name not in seen:
                seen.add(name)
                order.append(name)
            if len(order) == len(self._members):
                break
        return order

    def _first_alive(self, order: List[str],
                     exclude=()) -> Optional[_Member]:
        for name in order:
            mem = self._members[name]
            if mem.alive and name not in exclude:
                return mem
        return None

    def _route(self, handle: Hashable) -> Optional[_Member]:
        """The member that serves ``handle`` right now: first ALIVE
        member in ring order that has it registered; None when no
        survivor serves it."""
        for name in self.ring_order(handle):
            mem = self._members[name]
            if mem.alive and handle in mem.session:
                return mem
        for mem in self._members.values():  # placement drifted off-ring
            if mem.alive and handle in mem.session:
                return mem
        return None

    def alive(self) -> List[str]:
        return [n for n, m in self._members.items() if m.alive]

    def member(self, name: str) -> Session:
        return self._members[name].session

    def placement_of(self, handle: Hashable) -> List[str]:
        with self._lock:
            return list(self._placement.get(handle, ()))

    # -- registration -------------------------------------------------------

    def register(self, A, op: str = "auto",
                 handle: Optional[Hashable] = None,
                 member: Optional[str] = None, **kwargs) -> Hashable:
        """Register an operator fleet-wide: consistent-hash placement
        picks the owning member (``member=`` pins it — the drill/ops
        escape hatch), the spec is retained for failover re-register.
        Handles must be str/int (the checkpoint-restorable set)."""
        with self._lock:
            if handle is None:
                self._seq += 1
                handle = f"h{self._seq}"
            if not isinstance(handle, (str, int)) \
                    or isinstance(handle, bool):
                raise SlateError(
                    "Fleet.register: handles must be str/int (the "
                    f"checkpoint-restorable set), got {type(handle)}")
            if handle in self._specs:
                raise SlateError(f"Fleet.register: handle {handle!r} "
                                 "already registered")
            target = (self._members[member] if member is not None
                      else self._first_alive(self.ring_order(handle)))
            if target is None or not target.alive:
                raise SlateError("Fleet.register: no alive member")
            target.session.register(A, op=op, handle=handle, **kwargs)
            resolved_op = target.session.op_meta(handle)[0]
            self._specs[handle] = _Spec(A, resolved_op, dict(kwargs))
            self._placement[handle] = [target.name]
            self._by_repr[repr(handle)] = handle
            self.metrics.inc("fleet_handles_registered")
        return handle

    def warmup(self, handles=None):
        """AOT warmup on every member currently serving each handle."""
        with self._lock:
            todo = list(self._placement.items() if handles is None
                        else ((h, self._placement.get(h, []))
                              for h in handles))
        for h, places in todo:
            for name in places:
                mem = self._members[name]
                if mem.alive:
                    mem.session.warmup(h)

    # -- replication (heat-driven) ------------------------------------------

    def _replica_dir(self, handle: Hashable, target: str) -> str:
        """The RETAINED per-(handle, replica) base checkpoint
        directory (round 20): created under ``checkpoint_root`` when
        the coordinator has one, else under a coordinator-owned temp
        root (:meth:`close` removes it). The handle component is its
        ring hash — filesystem-safe for any str/int handle."""
        if self.checkpoint_root is not None:
            base = os.path.join(self.checkpoint_root, "_replica_bases")
        else:
            with self._lock:
                if self._xfer_root is None:
                    self._xfer_root = tempfile.mkdtemp(
                        prefix="slate_fleet_bases_")
                base = self._xfer_root
        return os.path.join(base, target,
                            f"h{_hval(repr(handle)):016x}")

    def replicate(self, handle: Hashable) -> Optional[str]:
        """Replicate one handle onto its next ring member via a
        checkpoint transfer (byte-identical resident, heat/health
        included); falls back to register+warm when the primary holds
        no resident yet. The transferred checkpoint is RETAINED as the
        replica edge's delta base (round 20): a later :meth:`update`
        ships only the blobs the update changed. Returns the replica
        member name (None when every alive member already serves the
        handle)."""
        with self._lock:
            places = self._placement.get(handle)
            spec = self._specs.get(handle)
            if not places or spec is None:
                return None
            primary = self._members[places[0]]
            target = self._first_alive(self.ring_order(handle),
                                       exclude=set(places))
            if target is None:
                return None
        if handle in primary.session.cached_handles():
            bdir = self._replica_dir(handle, target.name)
            manifest = primary.session.checkpoint(bdir, only=[handle],
                                                  host=primary.name)
            target.session.restore(bdir, only=[handle])
            with self._lock:
                self._replica_base[(handle, target.name)] = (bdir,
                                                             manifest)
        else:
            target.session.register(spec.A, op=spec.op, handle=handle,
                                    **spec.kwargs)
            target.session.warmup(handle)
        with self._lock:
            self._placement[handle].append(target.name)
        self.metrics.inc("fleet_replicas_created")
        return target.name

    def replicate_hot(self, top_k: int = 1) -> List[Hashable]:
        """Replicate the fleet's top-K hottest handles (the merged
        round-15 placement rows, heat-sorted, are the input — ROADMAP
        item 1's 'invert the fold into a placement input')."""
        doc = self.placement()
        rows = sorted(doc.get("rows", []),
                      key=lambda r: (-(float(r.get("heat") or 0.0)),
                                     str(r.get("handle", ""))))
        made, seen = [], set()
        for row in rows:
            h = self._by_repr.get(str(row.get("handle", "")))
            if h is None or h in seen:
                continue
            seen.add(h)
            if self.replicate(h) is not None:
                made.append(h)
            if len(made) >= top_k:
                break
        return made

    # -- incremental-update replication (round 20) --------------------------

    def update(self, handle: Hashable, delta=None, **kwargs) -> dict:
        """Apply an incremental factor update (Session.update: chol
        rank-k up/downdate, qr row append/delete) on the handle's
        PRIMARY, then propagate the mutated resident to every replica
        as a DELTA checkpoint — blob-level sha256 diff against the
        retained base each replica edge keeps, so the sync ships only
        what the update changed (for an appended-QR resident that is
        the append block, never the base factor;
        ``fleet_delta_sync_bytes`` vs ``fleet_full_sync_bytes`` is the
        wire saving, bench-artifact pinned). A replica edge with no
        usable base (never full-transferred, or injected-stale via the
        seeded ``replica_stale`` fault at site ``fleet.replica``)
        falls back to a counted full re-transfer that BECOMES the new
        retained base. Returns the primary's update result dict."""
        with self._lock:
            places = list(self._placement.get(handle, ()))
        if not places:
            raise SlateError(
                f"Fleet.update: unknown handle {handle!r}")
        primary = self._members[places[0]]
        if not primary.alive:
            raise SlateError(
                f"Fleet.update: primary of {handle!r} is dead; run "
                "failover (kill) before mutating")
        out = primary.session.update(handle, delta, **kwargs)
        if out.get("deferred"):
            return out  # no resident mutated -> nothing to propagate
        for name in places[1:]:
            mem = self._members[name]
            if mem.alive and handle in mem.session:
                self._sync_replica(handle, primary, mem)
        return out

    def _sync_replica(self, handle: Hashable, primary: _Member,
                      target: _Member):
        """One replica edge's post-update sync: delta checkpoint
        against the retained base when one exists (the target's queued
        requests drain against its still-resident factor first — zero
        lost futures — then the stale resident is swapped for the
        restored one), full re-transfer otherwise. Either way the
        retained base is refreshed to the post-update state so the
        NEXT update's delta is minimal."""
        from .checkpoint import (_iter_blob_descs as _iter_manifest_blobs,
                                 restore_session_delta,
                                 save_session_delta)
        key = (handle, target.name)
        with self._lock:
            base = self._replica_base.get(key)
        if base is not None and self.faults is not None and any(
                s.kind == "replica_stale"
                for s in self.faults.fire("fleet.replica")):
            # injected-stale retained base: never diff against bits
            # the replica might not actually hold — counted, and the
            # full re-transfer below re-establishes a trusted base
            self.metrics.inc("fleet_delta_base_stale_total")
            base = None
        synced = False
        if base is not None:
            bdir, base_manifest = base
            ddir = tempfile.mkdtemp(prefix="slate_delta_")
            try:
                _, stats = save_session_delta(
                    primary.session, ddir, base_manifest,
                    only=[handle], host=primary.name)
                # restore skips registered handles (live-operator-wins
                # conflict rule), so the replica's stale copy must
                # leave first — AFTER its queued work drains against
                # the still-resident factor (zero lost futures)
                self._drain_member(target)
                target.session.unregister(handle)
                summary = restore_session_delta(target.session, ddir,
                                                bdir, only=[handle])
                if handle in summary["restored"]:
                    synced = True
                    self.metrics.inc("fleet_delta_replications_total")
                    self.metrics.inc("fleet_delta_sync_bytes",
                                     stats["sync_bytes"])
                    self.metrics.inc("fleet_full_sync_bytes",
                                     stats["full_bytes"])
                    rec = self.recorder
                    if rec is not None:
                        rec.decision(
                            "delta_sync", handle=handle,
                            outcome=target.name,
                            inputs={"primary": primary.name,
                                    "sync_bytes": stats["sync_bytes"],
                                    "full_bytes": stats["full_bytes"]})
            finally:
                shutil.rmtree(ddir, ignore_errors=True)
        if not synced:
            # the recovery floor: full checkpoint transfer, which is
            # ALSO the new retained base for this edge
            bdir = self._replica_dir(handle, target.name)
            manifest = primary.session.checkpoint(
                bdir, only=[handle], host=primary.name)
            self._drain_member(target)
            target.session.unregister(handle)
            target.session.restore(bdir, only=[handle])
            self.metrics.inc("fleet_full_replications_total")
            self.metrics.inc(
                "fleet_full_sync_bytes",
                sum(int(b.get("nbytes", 0))
                    for rec in manifest.get("records", [])
                    for k_ in ("operator", "payload")
                    for b in _iter_manifest_blobs(rec.get(k_))))
            jrec = self.recorder
            if jrec is not None:
                # the delta-vs-full CHOICE: full because no trusted
                # base existed or the delta restore fell through
                jrec.decision("full_sync", handle=handle,
                              outcome=target.name,
                              inputs={"primary": primary.name,
                                      "had_base": base is not None})
            with self._lock:
                self._replica_base[key] = (bdir, manifest)
            return
        # refresh the retained base in place: the next delta diffs
        # against the state BOTH sides now hold (blob content is what
        # resolves — the manifest records the new generation)
        bdir, _ = base
        manifest = primary.session.checkpoint(bdir, only=[handle],
                                              host=primary.name)
        with self._lock:
            self._replica_base[key] = (bdir, manifest)

    def close(self):
        """Remove the coordinator-owned retained-base temp root (a
        ``checkpoint_root`` fleet keeps its bases — they are part of
        the durable checkpoint tree)."""
        with self._lock:
            root, self._xfer_root = self._xfer_root, None
            self._replica_base.clear()
        if root is not None:
            shutil.rmtree(root, ignore_errors=True)

    # -- migration-on-eviction (round 18: HBM-pressure rebalancing) ---------

    def _least_loaded(self, exclude=()) -> Optional[_Member]:
        """The alive member with the most per-chip HBM headroom (an
        unbounded member counts its resident bytes as negative load) —
        the migration TARGET choice the merged placement rows imply."""
        best, best_key = None, None
        for name, mem in sorted(self._members.items()):
            if not mem.alive or name in exclude:
                continue
            head = mem.session.hbm_headroom()
            # sort by (bounded-headroom desc, resident bytes asc):
            # an unbounded session beats any pressured bounded one
            key = ((-head if head is not None else float("-inf")),
                   mem.session.cached_bytes)
            if best_key is None or key < best_key:
                best, best_key = mem, key
        return best

    def _drain_member(self, mem: _Member):
        """Dispatch everything queued on one member (caller's thread,
        the pump discipline) so a migration can unregister the source
        handle with zero lost futures — every queued request against
        it resolves from the still-resident source factor first."""
        while True:
            batches = mem.batcher.pop_ready(force=True)
            if not batches:
                break
            for key, reqs in batches:
                try:
                    mem.batcher.run(key, reqs)
                except Exception as e:  # noqa: BLE001 — futures carry it
                    for r in reqs:
                        if not r.future.done():
                            try:
                                r.future.set_exception(e)
                                mem.session.metrics.inc(
                                    "failed_requests_total")
                            except InvalidStateError:
                                pass

    def migrate(self, handle: Hashable,
                target: Optional[str] = None) -> Optional[str]:
        """Move one handle's primary residency to another member via
        the round-17 checkpoint-transfer path — the resident factor
        arrives BYTE-IDENTICAL (no refactor on the target, pinned) and
        routed requests follow the move (new submits route to the
        target; requests already queued on the source drain against
        the still-resident source factor before it is released — zero
        lost futures, zero wrong answers). Target defaults to the
        least-loaded alive member.

        A ``migration_abort`` fault (site ``fleet.migrate``, consulted
        once per transfer attempt) kills the attempt mid-flight: the
        source keeps serving untouched, the coordinator retries ONCE
        (``fleet_migration_retries_total``) — the per-record checksum
        + register-then-insert restore order mean a half-resident can
        never exist on the target. Returns the target member name, or
        None when the migration could not run (no target, cold handle
        with no spec, or both attempts aborted)."""
        with self._lock:
            places = list(self._placement.get(handle, ()))
            spec = self._specs.get(handle)
        if not places or spec is None:
            return None
        source = self._members[places[0]]
        if not source.alive:
            return None  # kill() owns dead-member recovery
        if target is not None:
            tmem = self._members[target]
            if not tmem.alive or target in places:
                return None
        else:
            tmem = self._least_loaded(exclude=set(places))
            if tmem is None:
                return None
        resident = handle in source.session.cached_handles()
        moved = False
        for attempt in range(2):
            if self.faults is not None and any(
                    s.kind == "migration_abort"
                    for s in self.faults.fire("fleet.migrate")):
                # mid-transfer death: the target saw nothing durable
                # (restore registers only checksum-verified records),
                # the source is untouched and KEEPS SERVING; counted,
                # and the second pass is the counted retry
                self.metrics.inc("fleet_migration_aborts_total")
                rec = self.recorder
                if rec is not None:
                    rec.decision(
                        "migration_abort", handle=handle,
                        outcome="retry" if attempt == 0 else "gave_up",
                        inputs={"source": source.name,
                                "target": tmem.name,
                                "attempt": attempt})
                if attempt == 0:
                    self.metrics.inc("fleet_migration_retries_total")
                    continue
                _obs_log.warning(
                    "fleet: migration of %r aborted twice; source %r "
                    "keeps serving", handle, source.name)
                return None
            if resident:
                xfer = tempfile.mkdtemp(prefix="slate_migrate_")
                try:
                    source.session.checkpoint(xfer, only=[handle],
                                              host=source.name)
                    summary = tmem.session.restore(xfer, only=[handle])
                finally:
                    shutil.rmtree(xfer, ignore_errors=True)
                if handle not in summary["registered"]:
                    return None
                moved = handle in summary["restored"]
            else:
                # cold handle: nothing resident to move — re-register
                # the retained spec (the target refactors on first
                # touch, same as the recovery floor)
                tmem.session.register(spec.A, op=spec.op, handle=handle,
                                      **spec.kwargs)
            break
        # route new traffic to the target BEFORE releasing the source
        with self._lock:
            self._placement[handle] = [tmem.name] + [
                p for p in self._placement.get(handle, ())
                if p not in (source.name, tmem.name)]
        # drain requests already queued on the source against its
        # still-resident factor, then release the source's copy
        self._drain_member(source)
        src_res = source.session._cache.get(handle)
        if src_res is not None:
            self.metrics.inc("fleet_migrated_bytes", src_res.nbytes)
        source.session.unregister(handle)
        self.metrics.inc("fleet_migrations_total")
        if moved:
            self.metrics.inc("fleet_migrations_warm")
        rec = self.recorder
        if rec is not None:
            rec.decision("migration", handle=handle,
                         outcome="warm" if moved else "cold",
                         inputs={"source": source.name,
                                 "target": tmem.name,
                                 "resident": resident})
        _obs_log.warning(
            "fleet: migrated %r from %r to %r (%s)", handle,
            source.name, tmem.name,
            "byte-identical resident" if moved else "cold re-register")
        return tmem.name

    def migrate_coldest(self, source: str, k: int = 1,
                        target: Optional[str] = None) -> List[Hashable]:
        """Migrate the ``k`` COLDEST residents of one member (the
        round-15 heat rows rank them — migration evicts the source's
        least valuable HBM first, the inverse of replicate_hot's
        hottest-first) to ``target`` (default least-loaded). Returns
        the handles that moved."""
        mem = self._members[source]
        rows = mem.session.placement_snapshot(host=source)["rows"]
        rows.sort(key=lambda r: (float(r.get("heat") or 0.0),
                                 str(r.get("handle", ""))))
        moved = []
        for row in rows:
            if len(moved) >= k:
                break
            h = self._by_repr.get(str(row.get("handle", "")))
            if h is None:
                continue
            with self._lock:
                places = self._placement.get(h, ())
                if not places or places[0] != source:
                    continue  # this member is only a replica holder
            if self.migrate(h, target=target) is not None:
                moved.append(h)
        return moved

    def migrate_pressured(self, headroom_floor: int = 0,
                          k: int = 1) -> Dict[str, List[Hashable]]:
        """The migration-on-eviction reflex: every alive member whose
        per-chip HBM headroom (resident factors + largest program
        transient vs its budget) is at or below ``headroom_floor``
        migrates its ``k`` coldest residents to the least-loaded
        member — instead of evicting them into refactor-on-miss, the
        pre-round-18 failure mode. Heat + placement snapshots drive
        the source/coldest/target choices; the checkpoint-transfer
        path keeps every moved resident byte-identical. Returns
        {member: [migrated handles]}."""
        out: Dict[str, List[Hashable]] = {}
        for name, mem in sorted(self._members.items()):
            if not mem.alive:
                continue
            head = mem.session.hbm_headroom()
            if head is None or head > headroom_floor:
                continue
            moved = self.migrate_coldest(name, k=k)
            if moved:
                out[name] = moved
        return out

    # -- checkpoints --------------------------------------------------------

    def _checkpoint_base(self, mem: _Member) -> Optional[str]:
        if mem.session.checkpoint_dir is not None:
            return mem.session.checkpoint_dir
        if self.checkpoint_root is not None:
            return os.path.join(self.checkpoint_root, mem.name)
        return None

    def _checkpoint_of(self, mem: _Member) -> Optional[str]:
        """The newest on-disk checkpoint this member left, or None.
        Falls back from the coordinator-recorded path to the derivable
        ``<base>/checkpoint`` location, so a checkpoint flushed by a
        prior coordinator incarnation or by ``Session.close()`` (the
        orderly-shutdown flush) is still found by failover."""
        path = mem.checkpoint_path
        if path is None:
            base = self._checkpoint_base(mem)
            if base is not None:
                path = os.path.join(base, "checkpoint")
        if path is not None \
                and os.path.exists(os.path.join(path, MANIFEST_NAME)):
            return path
        return None

    def checkpoint_all(self) -> Dict[str, Optional[str]]:
        """Flush every alive member's checkpoint (to its session's
        ``checkpoint_dir`` or ``<checkpoint_root>/<name>``); returns
        {member: path or None}. The paths are what :meth:`kill`'s
        failover restores from."""
        out: Dict[str, Optional[str]] = {}
        for mem in self._members.values():
            if not mem.alive:
                continue
            base = self._checkpoint_base(mem)
            if base is None:
                out[mem.name] = None
                continue
            path = os.path.join(base, "checkpoint")
            mem.session.checkpoint(path, host=mem.name)
            mem.checkpoint_path = path
            out[mem.name] = path
        return out

    # -- serving ------------------------------------------------------------

    def submit(self, handle: Hashable, b, timeout_s=None,
               tenant=None) -> Future:
        """Enqueue one solve, routed by placement. Returns a FLEET
        future: it survives the serving member's death (re-routed to a
        survivor, counted) — it resolves with the answer, or with the
        survivor's counted rejection (shed/deadline), never silently
        hangs (the zero-lost-futures contract chaos exit-gates)."""
        rec = _FleetRequest(handle, b, {
            k: v for k, v in (("timeout_s", timeout_s),
                              ("tenant", tenant)) if v is not None})
        target = self._route(handle)
        if target is None:
            rec.future.set_exception(SlateError(
                f"Fleet: no alive member serves handle {handle!r}"))
            return rec.future
        self._send(rec, target)
        return rec.future

    def _send(self, rec: _FleetRequest, mem: _Member):
        mfut = mem.batcher.submit(rec.handle, rec.b, **rec.kwargs)
        rec.member, rec.mfut = mem.name, mfut
        with self._lock:
            self._inflight[mem.name].append(rec)
        mfut.add_done_callback(
            lambda mf, r=rec: self._complete(r, mf))

    @staticmethod
    def _complete(rec: _FleetRequest, mf: Future):
        if mf.cancelled():
            return  # re-routed after a member death: a successor owns it
        try:
            e = mf.exception()
            if e is not None:
                rec.future.set_exception(e)
            else:
                rec.future.set_result(mf.result())
        except InvalidStateError:
            pass  # client cancelled the fleet future concurrently

    def pump(self, force: bool = False):
        """Drive every alive member's Batcher one step (shed check +
        ready-bucket dispatch) on the caller's thread. A bucket whose
        dispatch raises fails its still-unresolved futures (counted) —
        the no-thread analog of the Executor's final-failure path."""
        for mem in self._members.values():
            if not mem.alive:
                continue
            # round 23: one history-sampling pass per pump (a member
            # without enable_timeseries pays one is-None check) — the
            # thread-free sampler rides the same caller-thread step
            # the Batcher does, so chaos drives it deterministically
            mem.session.pump_timeseries()
            mem.batcher.maybe_shed()
            for key, reqs in mem.batcher.pop_ready(force=force):
                try:
                    mem.batcher.run(key, reqs)
                except Exception as e:  # noqa: BLE001 — futures carry it
                    for r in reqs:
                        if not r.future.done():
                            try:
                                r.future.set_exception(e)
                                mem.session.metrics.inc(
                                    "failed_requests_total")
                            except InvalidStateError:
                                pass
        with self._lock:  # prune resolved in-flight records
            for name in list(self._inflight):
                live = [r for r in self._inflight[name]
                        if not r.future.done()]
                if live:
                    self._inflight[name] = live
                else:
                    del self._inflight[name]

    def flush(self):
        """Dispatch everything queued on alive members until drained."""
        self.pump(force=True)
        while any(m.batcher.pending() for m in self._members.values()
                  if m.alive):
            self.pump(force=True)

    # -- failover -----------------------------------------------------------

    def kill(self, name: str):
        """Declare member ``name`` dead (the crash reflex): its queued
        requests are orphaned and re-routed to survivors, its handles
        walk the recovery ladder (replica → checkpoint restore → cold
        re-register), all counted. Idempotent."""
        with self._lock:
            mem = self._members[name]
            if not mem.alive:
                return
            mem.alive = False
            self.metrics.inc("fleet_process_deaths_total")
            self.metrics.set_gauge("fleet_alive_members",
                                   len(self.alive()))
            orphans = [r for r in self._inflight.pop(name, [])
                       if not r.future.done()]
            for r in orphans:
                if r.mfut is not None:
                    r.mfut.cancel()  # detach: the dead queue never runs
            affected = sorted(
                (h for h, places in self._placement.items()
                 if name in places), key=repr)
            # the ladder applies only where the dead member was the
            # ROUTING PRIMARY (places[0]); a dead replica never served,
            # so losing it is a durability decrement, not a failover
            was_primary = {h for h in affected
                           if self._placement[h][0] == name}
            for h in affected:
                self._placement[h] = [p for p in self._placement[h]
                                      if p != name]
            # retained delta bases whose replica died are garbage
            # (content-addressing makes a stale base SAFE, but a dead
            # edge's base is never diffed again — drop the references)
            for key in [k for k in self._replica_base
                        if k[1] == name]:
                del self._replica_base[key]
        _obs_log.warning(
            "fleet: member %r declared dead (%d orphaned requests, "
            "%d affected handles); running failover", name,
            len(orphans), len(affected))
        self._failover_handles(mem, affected, was_primary)
        # re-route the orphans AFTER the handles recovered (a replica
        # or restored resident serves them without refactor); resolving
        # futures runs client callbacks, so this stays outside the lock
        for r in orphans:
            self.metrics.inc("fleet_failover_requests_total")
            target = self._route(r.handle)
            if target is None:
                try:
                    r.future.set_exception(SlateError(
                        f"Fleet: handle {r.handle!r} lost with member "
                        f"{name!r} and no survivor serves it"))
                except InvalidStateError:
                    pass
                continue
            self._send(r, target)

    def _failover_handles(self, dead: _Member, affected, was_primary):
        """The recovery ladder for each handle the dead member served
        (sorted order — deterministic under a seeded injector).
        ``was_primary``: the subset of ``affected`` the dead member
        actually ROUTED for — only those walk the ladder; a handle
        that merely lost its replica here keeps serving from its
        untouched primary (counted ``fleet_replicas_lost``)."""
        from .checkpoint import load_manifest
        ckpt = self._checkpoint_of(dead)
        manifest = None
        if ckpt is not None:
            try:  # parsed+validated ONCE; per-handle restores reuse it
                manifest = load_manifest(ckpt)
            except SlateError as e:
                _obs_log.warning(
                    "fleet: checkpoint of dead member %r is unreadable "
                    "(%s); falling through to cold re-register",
                    dead.name, e)
                ckpt = None
        rec = self.recorder
        for h in affected:
            if h not in was_primary:
                # only a replica died — the primary never stopped
                # serving; no ladder, no stale check, just a counted
                # durability decrement
                self.metrics.inc("fleet_replicas_lost")
                continue
            self.metrics.inc("fleet_failover_handles_total")
            with self._lock:
                places = list(self._placement.get(h, ()))
            if places:
                # rung 1: a surviving replica serves immediately, no
                # refactor — unless it is (injected-)stale, in which
                # case the counted refresh evicts the stale resident
                # so the next touch refactors from the registered
                # operand (never serve stale bits)
                stale = (self.faults is not None
                         and any(s.kind == "replica_stale" for s in
                                 self.faults.fire("fleet.replica")))
                if not stale:
                    self.metrics.inc("fleet_failover_replica_served")
                    # ONE failover decision per counted handle; the
                    # rung taken rides the outcome (OUTCOME_COUNTERS
                    # carries the per-rung counter parity)
                    if rec is not None:
                        rec.decision("failover", handle=h,
                                     outcome="replica",
                                     inputs={"dead": dead.name,
                                             "replicas": places})
                    continue
                self.metrics.inc("fleet_replica_stale_refreshes")
                _obs_log.warning(
                    "fleet: replica of %r is stale; refreshing "
                    "(evict + refactor-on-miss)", h)
                if rec is not None:
                    rec.decision("failover", handle=h,
                                 outcome="stale_refresh",
                                 inputs={"dead": dead.name,
                                         "replicas": places})
                for pname in places:
                    self._members[pname].session.evict(h)
                continue
            target = self._first_alive(self.ring_order(h))
            if target is None:
                _obs_log.warning("fleet: no survivor for handle %r", h)
                if rec is not None:
                    rec.decision("failover", handle=h,
                                 outcome="no_survivor",
                                 inputs={"dead": dead.name})
                continue
            registered = False
            if ckpt is not None:
                # rung 2: warm-restart from the dead member's last
                # checkpoint (no refactor; a corrupt record is caught
                # by its checksum inside restore and degrades to
                # refactor-on-miss, counted there)
                summary = target.session.restore(ckpt, only=[h],
                                                 manifest=manifest)
                if h in summary["registered"]:
                    registered = True
                    if h in summary["restored"]:
                        self.metrics.inc("fleet_failover_restored")
                        if rec is not None:
                            rec.decision("failover", handle=h,
                                         outcome="restored",
                                         inputs={"dead": dead.name,
                                                 "target": target.name})
                    else:
                        self.metrics.inc("fleet_failover_refactor")
                        if rec is not None:
                            rec.decision("failover", handle=h,
                                         outcome="refactor",
                                         inputs={"dead": dead.name,
                                                 "target": target.name})
            if not registered:
                # rung 3 (the floor): re-register the retained spec
                # cold — counted refactor-on-miss on first touch
                spec = self._specs.get(h)
                if spec is None:
                    if rec is not None:
                        rec.decision("failover", handle=h,
                                     outcome="no_spec",
                                     inputs={"dead": dead.name})
                    continue
                try:
                    target.session.register(spec.A, op=spec.op,
                                            handle=h, **spec.kwargs)
                except SlateError as e:
                    _obs_log.warning(
                        "fleet: cold re-register of %r failed (%s)",
                        h, e)
                    if rec is not None:
                        rec.decision("failover", handle=h,
                                     outcome="register_failed",
                                     inputs={"dead": dead.name,
                                             "error": str(e)})
                    continue
                self.metrics.inc("fleet_failover_cold")
                if rec is not None:
                    rec.decision("failover", handle=h, outcome="cold",
                                 inputs={"dead": dead.name,
                                         "target": target.name})
            with self._lock:
                self._placement[h] = [target.name]

    # -- fleet views --------------------------------------------------------

    def placement(self) -> dict:
        """The merged fleet placement doc: alive members' live
        placement snapshots plus checkpoint-derived PARTIAL docs for
        dead members that left one (the crash-window fold — satellite:
        a host whose live snapshot is gone but whose checkpoint
        survives still contributes rows, marked partial)."""
        from ..obs.aggregate import (merge_placement_snapshots,
                                     placement_from_checkpoint)
        from .checkpoint import load_manifest
        docs = []
        for mem in self._members.values():
            if mem.alive:
                docs.append(mem.session.placement_snapshot(
                    host=mem.name))
            else:
                ckpt = self._checkpoint_of(mem)
                if ckpt is None:
                    continue
                try:
                    manifest = load_manifest(ckpt)
                except SlateError:
                    continue
                docs.append(placement_from_checkpoint(manifest,
                                                      host=mem.name))
        return merge_placement_snapshots(docs)

    def timeseries_payload(self) -> dict:
        """Fleet history fold (round 23): every member's time-series
        store host-labeled into one
        ``slate_tpu.timeseries.fleet.v1`` document with EXACT
        conservation on the summed counter series (the round-12 fold
        discipline). Members without a store — or dead — contribute
        ``None`` and are counted ``partial_processes`` (the round-17
        partial-host tolerance)."""
        from ..obs.aggregate import merge_timeseries_payloads
        names = list(self._members)
        payloads = []
        for name in names:
            mem = self._members[name]
            ts = mem.session.timeseries
            payloads.append(ts.payload()
                            if mem.alive and ts is not None else None)
        return merge_timeseries_payloads(payloads, hosts=names)

    def snapshot(self) -> dict:
        """JSON view of the coordinator: members, placement, ring
        assignment, and the fleet counters — the bench/chaos artifact
        section."""
        with self._lock:
            placement = {repr(h): list(p)
                         for h, p in sorted(self._placement.items(),
                                            key=lambda kv: repr(kv[0]))}
        snap = self.metrics.snapshot()
        return {
            "schema": "slate_tpu.fleet.v1",
            "members": {n: {"alive": m.alive,
                            "checkpoint": m.checkpoint_path}
                        for n, m in self._members.items()},
            "placement": placement,
            "counters": snap["counters"],
            "gauges": snap["gauges"],
        }
