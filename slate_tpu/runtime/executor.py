"""Async submit/future front end for the solve service.

An ``Executor`` owns a background worker thread that drives the
Batcher: callers ``submit(handle, b)`` and get a
``concurrent.futures.Future``; the worker sleeps until a bucket is full
or its max-wait deadline expires, then dispatches it as one stacked
Session solve. Transient dispatch failures (a flaky device tunnel, an
interrupted transfer) are retried a bounded number of times before the
batch's futures are failed.

``warmup`` is the AOT path: for each registered shape bucket it factors
the operator and ``jit(...).lower(...).compile()``s the solve off the
request path (Session.warmup), so the first real request pays neither
factorization nor compilation.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Hashable, Iterable, Optional

from .batching import Batcher
from .session import Session


class Executor:
    """Background-thread serving front end over a Session.

    Usage::

        sess = Session(hbm_budget=2 << 30)
        h = sess.register(A, op="chol")
        with Executor(sess, max_batch=32, max_wait=2e-3) as ex:
            ex.warmup([h])
            futs = [ex.submit(h, b) for b in rhs_stream]
            xs = [f.result() for f in futs]
    """

    def __init__(self, session: Session, max_batch: int = 32,
                 max_wait: float = 2e-3, retries: int = 2,
                 pad_widths: bool = False):
        self.session = session
        self.retries = retries
        self.batcher = Batcher(session, max_batch=max_batch,
                               max_wait=max_wait, pad_widths=pad_widths)
        self._cv = threading.Condition()
        self._stop = False
        self._inflight = 0  # batches detached from the Batcher, unsolved
        self._thread = threading.Thread(target=self._run,
                                        name="slate-tpu-serve", daemon=True)
        self._thread.start()

    # -- client surface ----------------------------------------------------

    def submit(self, handle: Hashable, b) -> Future:
        """Enqueue one solve request; never blocks on the device. The
        shutdown check and the enqueue are one atomic step under the
        lock, so a request can never land in a drained Batcher after
        the worker has exited (its Future would hang forever)."""
        with self._cv:
            if self._stop:
                raise RuntimeError("Executor is shut down")
            fut = self.batcher.submit(handle, b)
            self._cv.notify_all()
        return fut

    def warmup(self, handles: Iterable[Hashable], nrhs: int = 1):
        """AOT compile the solve for each handle's (rows, nrhs) bucket
        (tile padding makes nrhs=1 cover widths up to nb for dense
        operators — see Session.warmup)."""
        for h in handles:
            self.session.warmup(h, nrhs)

    def flush(self):
        """Block until everything queued at call time has been solved
        (queued buckets AND batches already detached to the worker)."""
        with self._cv:
            self._cv.notify_all()
            while self.batcher.pending() or self._inflight:
                self._cv.wait(timeout=0.05)

    def shutdown(self, wait: bool = True):
        """Stop the worker; pending requests are force-dispatched."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if wait:
            self._thread.join()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False

    # -- worker ------------------------------------------------------------

    def _run(self):
        while True:
            with self._cv:
                if not self._stop:
                    deadline = self.batcher.next_deadline()
                    if deadline is None:
                        self._cv.wait()
                    else:
                        timeout = deadline - time.monotonic()
                        if timeout > 0:
                            self._cv.wait(timeout)
                stopping = self._stop
                # detach + count in-flight under the SAME lock hold, so
                # flush() never observes pending()==0 while a batch sits
                # between pop_ready and dispatch
                batches = self.batcher.pop_ready(force=stopping)
                self._inflight += len(batches)
                if batches:
                    self.session.metrics.set_gauge("inflight_batches",
                                                   self._inflight)
            for key, reqs in batches:
                try:
                    self._dispatch(key, reqs)
                finally:
                    with self._cv:
                        self._inflight -= 1
                        self.session.metrics.set_gauge("inflight_batches",
                                                       self._inflight)
                        self._cv.notify_all()
            if stopping and not batches:
                with self._cv:
                    if not self.batcher.pending() and not self._inflight:
                        return

    def _dispatch(self, key, reqs):
        """Run one bucket with bounded retry on TRANSIENT dispatch
        failure (flaky tunnel, interrupted transfer). SlateError is
        deterministic — unknown handle, factorization info≠0 — and
        fails fast without retrying or touching the retries metric
        (DESIGN.md: retry covers dispatch, not numerical failure).

        Error capture (obs): a failed attempt's request spans are
        closed with the exception (status="error") by Batcher.run —
        inside the batch span's scope, so the exported tree stays
        properly nested — and each attempt opens fresh spans, so a
        retried request shows one errored span per failed attempt plus
        the final one."""
        from ..core.exceptions import SlateError

        tr = self.session.tracer

        def _fail_spans(e, attempt):
            for r in reqs:
                # Batcher.run already closed spans it opened (finish is
                # idempotent); this covers spans from a partial stack /
                # pre-dispatch failure, and detaches for the retry
                tr.finish_span(getattr(r, "span", None), error=e,
                               attempt=attempt)
                r.span = None  # the next attempt opens a fresh span

        err: Optional[BaseException] = None
        for attempt in range(self.retries + 1):
            try:
                self.batcher.run(key, reqs)
                return
            except SlateError as e:
                err = e
                _fail_spans(e, attempt)
                break
            except Exception as e:  # noqa: BLE001 — failed futures carry it
                err = e
                _fail_spans(e, attempt)
                if attempt < self.retries:
                    self.session.metrics.inc("retries")
        self.session.metrics.inc("failed_batches")
        slo = self.session.slo
        now = time.monotonic()
        for r in reqs:
            # cancelled/already-resolved requests are NOT service
            # failures — the success path skips them symmetrically
            # (Batcher.run's cancelled `continue`), so the SLO error
            # stream only counts requests this failure actually failed
            was_done = r.future.done()
            try:
                if not was_done:
                    r.future.set_exception(err)
            except Exception:  # client cancelled concurrently — same
                pass           # race Batcher.run guards on set_result
            if slo is not None and not was_done:
                # the final (post-retry) failure is the SLO error event
                meta = self.session.op_meta(getattr(r, "handle", None))
                if meta is not None:
                    slo.record_request(meta[0], meta[1],
                                       now - r.t_submit, ok=False)
