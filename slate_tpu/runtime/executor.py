"""Async submit/future front end for the solve service.

An ``Executor`` owns a background worker thread that drives the
Batcher: callers ``submit(handle, b)`` and get a
``concurrent.futures.Future``; the worker sleeps until a bucket is full
or its max-wait deadline expires (or a per-request deadline needs
failing fast), then dispatches it as one stacked Session solve.

Failure reflexes (round 14): transient dispatch failures (a flaky
device tunnel, an interrupted transfer) are retried with EXPONENTIAL
BACKOFF + JITTER; a per-(op, n) CIRCUIT BREAKER trips after repeated
dispatch failures and walks the declared degradation ladder
(``faults.DEGRADATION_LADDER``) instead of retry-storming a sick
path — grouped/dense buckets replay per-request, mixed operators
demote to working precision, mesh operators reject with a clear
error. The worker also drives the Batcher's load-shedding reflex
(one is-None check per wakeup when no ShedPolicy is set).

``warmup`` is the AOT path: for each registered shape bucket it factors
the operator and ``jit(...).lower(...).compile()``s the solve off the
request path (Session.warmup), so the first real request pays neither
factorization nor compilation.
"""

from __future__ import annotations

import random
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Hashable, Iterable, Optional, Tuple

from ..core.exceptions import SlateError
from .batching import Batcher, ShedPolicy, _SMALL
from .faults import DEGRADATION_LADDER
from .session import Session


class _Breaker:
    """Per-(op, n) circuit breaker. Touched ONLY by the Executor's
    single worker thread (dispatch is serialized), so no lock.

    closed → open after ``threshold`` consecutive final (post-retry)
    transient dispatch failures; open → half_open after ``cooldown_s``
    (one probe dispatch allowed through the normal path); the probe's
    outcome closes or re-opens. While open, buckets walk the
    degradation ladder instead of touching the failing path."""

    __slots__ = ("threshold", "cooldown_s", "failures", "state",
                 "opened_at")

    def __init__(self, threshold: int, cooldown_s: float):
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self.failures = 0
        self.state = "closed"
        self.opened_at = 0.0

    def allow(self, now: float) -> bool:
        if self.state == "closed":
            return True
        if self.state == "open" and now - self.opened_at \
                >= self.cooldown_s:
            self.state = "half_open"
            return True  # the probe
        return False

    def record_ok(self):
        self.failures = 0
        was = self.state
        self.state = "closed"
        return was != "closed"

    def record_failure(self, now: float) -> bool:
        """Returns True when this failure TRIPS the breaker open."""
        self.failures += 1
        if self.state == "half_open" or (self.state == "closed"
                                         and self.failures
                                         >= self.threshold):
            self.state = "open"
            self.opened_at = now
            return True
        if self.state == "open":
            self.opened_at = now
        return False


class Executor:
    """Background-thread serving front end over a Session.

    Usage::

        sess = Session(hbm_budget=2 << 30)
        h = sess.register(A, op="chol")
        with Executor(sess, max_batch=32, max_wait=2e-3) as ex:
            ex.warmup([h])
            futs = [ex.submit(h, b) for b in rhs_stream]
            xs = [f.result() for f in futs]

    ``retries`` bounds the transient-failure retry count per bucket;
    each retry sleeps ``backoff_base · 2^attempt`` (capped at
    ``backoff_max``) with multiplicative jitter in [0.5, 1.0) —
    deterministic when the session carries a FaultInjector, so chaos
    runs replay bit-for-bit. ``breaker_threshold`` consecutive
    exhausted-retry failures on one (op, n) trip its circuit breaker
    for ``breaker_cooldown`` seconds (see module docstring).
    ``shed_policy`` is handed to the Batcher (admission control +
    load shedding); ``timeout_s`` on submit is the per-request
    deadline."""

    def __init__(self, session: Session, max_batch: int = 32,
                 max_wait: float = 2e-3, retries: int = 2,
                 pad_widths: bool = False,
                 backoff_base: float = 0.01, backoff_max: float = 0.5,
                 breaker_threshold: int = 3,
                 breaker_cooldown: float = 1.0,
                 shed_policy: Optional[ShedPolicy] = None):
        self.session = session
        self.retries = retries
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown = breaker_cooldown
        self._breakers: dict = {}
        self.batcher = Batcher(session, max_batch=max_batch,
                               max_wait=max_wait, pad_widths=pad_widths,
                               shed_policy=shed_policy)
        self._cv = threading.Condition()
        self._stop = False
        self._kick = False  # work arrived since the worker last looked
        self._inflight = 0  # batches detached from the Batcher, unsolved
        self._thread = threading.Thread(target=self._run,
                                        name="slate-tpu-serve", daemon=True)
        self._thread.start()

    # -- client surface ----------------------------------------------------

    def submit(self, handle: Hashable, b,
               timeout_s: Optional[float] = None,
               tenant: Optional[str] = None) -> Future:
        """Enqueue one solve request; never blocks on the device. The
        shutdown check and the enqueue are one atomic step under the
        lock, so a request can never land in a drained Batcher after
        the worker has exited (its Future would hang forever).
        ``timeout_s``: per-request deadline (Batcher.submit).
        ``tenant``: per-request attribution override (round 15;
        Batcher.submit — an explicit tenant splits the bucket)."""
        with self._cv:
            if self._stop:
                raise RuntimeError("Executor is shut down")
            req, rejection = self.batcher.submit_deferred(
                handle, b, timeout_s=timeout_s, tenant=tenant)
            self._kick = True
            self._cv.notify_all()
        if rejection is not None:
            # resolve OUTSIDE the lock: a done-callback that re-enters
            # submit() would deadlock on the non-reentrant _cv
            self.batcher.reject_admission(req, rejection)
        return req.future

    def warmup(self, handles: Iterable[Hashable], nrhs: int = 1):
        """AOT compile the solve for each handle's (rows, nrhs) bucket
        (tile padding makes nrhs=1 cover widths up to nb for dense
        operators — see Session.warmup)."""
        for h in handles:
            self.session.warmup(h, nrhs)

    def flush(self):
        """Block until everything queued at call time has been solved
        (queued buckets AND batches already detached to the worker).
        Waits on the true next Batcher deadline instead of a fixed
        poll (the old 0.05 s timeout woke an idle caller 20×/s): the
        worker notifies after every dispatch and every queue
        transition notifies on submit, so the deadline wait is only
        the backstop for the bucket/request deadlines themselves."""
        with self._cv:
            self._kick = True
            self._cv.notify_all()
            while self.batcher.pending() or self._inflight:
                deadline = self.batcher.next_deadline()
                if deadline is None:
                    self._cv.wait()
                else:
                    self._cv.wait(max(deadline - time.monotonic(), 0.0)
                                  + 1e-3)

    def shutdown(self, wait: bool = True):
        """Stop the worker; pending requests are force-dispatched."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if wait:
            self._thread.join()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False

    # -- worker ------------------------------------------------------------

    def _run(self):
        while True:
            with self._cv:
                # a notify that fires while this thread is OUTSIDE
                # wait() (mid-dispatch) is consumed by nobody — the
                # _kick flag carries it across the gap, else a bucket
                # filled during a dispatch sleeps out its max_wait
                # deadline (with a large max_wait that is a flush()
                # deadlock, not a latency blip)
                if not self._stop and not self._kick:
                    deadline = self.batcher.next_deadline()
                    if deadline is None:
                        self._cv.wait()
                    else:
                        timeout = deadline - time.monotonic()
                        if timeout > 0:
                            self._cv.wait(timeout)
                self._kick = False
                stopping = self._stop
                # detach + count in-flight under the SAME lock hold, so
                # flush() never observes pending()==0 while a batch sits
                # between pop_ready and dispatch. Expired requests are
                # COLLECTED here and failed after the lock drops:
                # set_exception runs client done-callbacks, and one
                # that re-enters submit() would deadlock on _cv
                expired = []
                batches = self.batcher.pop_ready(force=stopping,
                                                 expired_out=expired)
                self._inflight += len(batches)
                if batches:
                    self.session.metrics.set_gauge("inflight_batches",
                                                   self._inflight)
            if expired:
                self.batcher._fail_expired(expired, time.monotonic())
            # the load-shedding reflex: one is-None check per wakeup
            # when no policy is configured (Batcher.maybe_shed) — and
            # re-checked between dispatches, because requests that
            # arrive while a long batch executes queue up behind it
            # (the exact population an overload shed must reach)
            self.batcher.maybe_shed()
            for key, reqs in batches:
                self.batcher.maybe_shed()
                try:
                    self._dispatch(key, reqs)
                finally:
                    with self._cv:
                        self._inflight -= 1
                        self.session.metrics.set_gauge("inflight_batches",
                                                       self._inflight)
                        self._cv.notify_all()
            if stopping and not batches:
                with self._cv:
                    if not self.batcher.pending() and not self._inflight:
                        return

    # -- dispatch: retry, breaker, degradation ladder ----------------------

    def _breaker_key(self, key, reqs=None) -> Optional[Tuple]:
        """(op, n) identity of a bucket — the circuit breaker's grain:
        a sick compiled program family is an (op, shape) property, not
        a per-handle one. Round 18: a bucket carrying an EXPLICIT
        tenant (the tenant rides the bucket key, so one bucket is one
        tenant) scopes its breaker to (op, n, tenant) — a noisy
        tenant's failing traffic trips ITS OWN breaker and walks the
        ladder alone instead of degrading every tenant's same-shape
        buckets with it."""
        if key and key[0] is _SMALL:
            bk = (key[1], key[2])
        else:
            bk = self.session.op_meta(key[0])
        if bk is not None and reqs:
            t = getattr(reqs[0], "tenant", None)
            if t is not None:
                bk = bk + (t,)
        return bk  # None for unknown handles (deterministic failure)

    def _publish_breakers(self):
        self.session.metrics.set_gauge(
            "circuit_breakers_open",
            sum(1 for b in self._breakers.values()
                if b.state != "closed"))

    def _backoff_sleep(self, attempt: int):
        """Exponential backoff with jitter before a retry. Jitter is
        multiplicative in [0.5, 1.0) — deterministic (injector-keyed)
        when fault injection is attached, so a chaos soak's retry
        timing replays."""
        delay = min(self.backoff_base * (2.0 ** attempt),
                    self.backoff_max)
        inj = self.session.faults
        u = inj.uniform("backoff") if inj is not None else random.random()
        delay *= 0.5 + 0.5 * u
        self.session.metrics.observe("retry_backoff_s", delay)
        time.sleep(delay)

    def _dispatch(self, key, reqs):
        """Run one bucket with exponential-backoff retry on TRANSIENT
        dispatch failure (flaky tunnel, interrupted transfer).
        SlateError is deterministic — unknown handle, factorization
        info≠0 — and fails fast without retrying or touching the
        retries metric (DESIGN.md: retry covers dispatch, not
        numerical failure).

        Circuit breaker: retry exhaustion records a failure against
        the bucket's (op, n) breaker; when the breaker TRIPS (or is
        already open) the bucket walks the degradation ladder
        (``faults.DEGRADATION_LADDER``) instead of failing its
        futures: grouped/dense → per-request replay, mixed →
        working-precision demotion, mesh → reject with a clear error.

        Error capture (obs): a failed attempt's request spans are
        closed with the exception (status="error") by Batcher.run —
        inside the batch span's scope, so the exported tree stays
        properly nested — and each attempt opens fresh spans, so a
        retried request shows one errored span per failed attempt plus
        the final one."""
        m = self.session.metrics
        tr = self.session.tracer
        now = time.monotonic()
        bk = self._breaker_key(key, reqs)
        br = self._breakers.get(bk) if bk is not None else None
        if br is not None and not br.allow(now):
            # open breaker: never touch the failing path — straight to
            # the degraded lane (fail-fast for mesh)
            m.inc("breaker_short_circuits")
            self._dispatch_degraded(key, reqs, None)
            return
        probing = br is not None and br.state == "half_open"
        if probing:
            m.inc("breaker_probes_total")
            rec = self.session.recorder
            if rec is not None:
                rec.decision("breaker_probe", handle=bk,
                             outcome="half_open",
                             inputs={"failures": br.failures})

        def _fail_spans(e, attempt):
            for r in reqs:
                # Batcher.run already closed spans it opened (finish is
                # idempotent); this covers spans from a partial stack /
                # pre-dispatch failure, and detaches for the retry
                tr.finish_span(getattr(r, "span", None), error=e,
                               attempt=attempt)
                r.span = None  # the next attempt opens a fresh span

        err: Optional[BaseException] = None
        for attempt in range(self.retries + 1):
            try:
                self.batcher.run(key, reqs)
                if br is not None and br.record_ok():
                    m.inc("breaker_closes_total")
                    self._publish_breakers()
                    rec = self.session.recorder
                    if rec is not None:
                        rec.decision("breaker_close", handle=bk,
                                     outcome="closed",
                                     inputs={"attempt": attempt})
                return
            except SlateError as e:
                err = e
                _fail_spans(e, attempt)
                break
            except Exception as e:  # noqa: BLE001 — failed futures carry it
                err = e
                _fail_spans(e, attempt)
                if attempt < self.retries:
                    m.inc("retries")
                    self._backoff_sleep(attempt)
        if err is not None and not isinstance(err, SlateError) \
                and bk is not None:
            # transient failure survived every retry: charge the breaker
            if br is None:
                br = self._breakers[bk] = _Breaker(
                    self.breaker_threshold, self.breaker_cooldown)
            if br.record_failure(time.monotonic()):
                m.inc("breaker_trips_total")
                self._publish_breakers()
                from ..obs.tracing import log as _obs_log
                _obs_log.warning(
                    "circuit breaker OPEN for %s after %d consecutive "
                    "dispatch failures; degrading per the ladder %s",
                    bk, br.failures, DEGRADATION_LADDER)
                rec = self.session.recorder
                if rec is not None:
                    rec.decision(
                        "breaker_open", handle=bk, outcome="open",
                        inputs={"failures": br.failures,
                                "error": f"{type(err).__name__}: "
                                         f"{err}",
                                "cooldown_s": self.breaker_cooldown})
                    # a breaker trip is an incident trigger (tentpole):
                    # capture the journal/flight context around it
                    rec.incident("breaker_open", key=str(bk),
                                 handle=bk,
                                 context={"failures": br.failures})
            if br.state == "open":
                # the tripping bucket itself takes the degraded lane —
                # its requests deserve the reflex, not the corpse of
                # the retry loop
                self._dispatch_degraded(key, reqs, err)
                return
        self._fail_batch(key, reqs, err)

    def _degrade_family(self, key) -> Optional[str]:
        """DEGRADATION_LADDER family of a bucket key (grouped buckets
        classify themselves; handle buckets ask the Session)."""
        if key and key[0] is _SMALL:
            return "grouped"
        return self.session.degrade_class(key[0])

    def _dispatch_degraded(self, key, reqs, err):
        """Walk one rung of faults.DEGRADATION_LADDER for a bucket
        whose breaker is open. Counted per rung; futures resolve
        exactly once either way."""
        m = self.session.metrics
        family = self._degrade_family(key)
        rung = DEGRADATION_LADDER.get(family or "", None)
        if rung == "per_request":
            # grouped/dense → per-request: B independent solves with
            # per-item isolation (Batcher.run_degraded)
            self.batcher.run_degraded(key, reqs)
            return
        if rung == "working_precision":
            # mixed → working precision: demote the operator (evict the
            # lo resident, deactivate refine) and replay per-request at
            # full precision
            self.session.demote_to_working_precision(key[0])
            self.batcher.run_degraded(key, reqs)
            return
        if rung == "reject":
            # mesh → reject: a sharded program has no cheaper
            # single-chip form of itself; fail fast with a clear error
            # instead of retry-storming a sick mesh
            m.inc("breaker_rejections_total")
            self._fail_batch(key, reqs, SlateError(
                f"circuit breaker open for mesh bucket {key!r}: "
                "degradation ladder is mesh→reject (no single-device "
                "degraded form of a sharded program) — re-register the "
                "operator without a mesh or retry after the cooldown"))
            return
        # unknown family (unregistered handle mid-flight): fail with
        # the original error — the deterministic path
        self._fail_batch(key, reqs, err if err is not None else
                         SlateError(f"Session: unknown bucket {key!r}"))

    def _fail_batch(self, key, reqs, err):
        """Final failure: fail every still-unresolved future with
        ``err`` and record the SLO error events (the round-12
        accounting: cancelled/already-resolved requests are NOT
        service failures)."""
        self.session.metrics.inc("failed_batches")
        slo = self.session.slo
        attr = self.session.attribution
        now = time.monotonic()
        for r in reqs:
            was_done = r.future.done()
            try:
                if not was_done:
                    r.future.set_exception(err)
                    self.session.metrics.inc("failed_requests_total")
                    if attr is not None:
                        attr.record_outcome(
                            self.session.request_tenant(
                                getattr(r, "handle", None),
                                getattr(r, "tenant", None)),
                            getattr(r, "handle", None), "failed")
            except InvalidStateError:
                pass  # client cancelled concurrently — same race
            except Exception:   # pragma: no cover - legacy guard
                pass            # (Batcher.run guards set_result alike)
            if slo is not None and not was_done:
                # the final (post-retry) failure is the SLO error event
                meta = self.session.op_meta(getattr(r, "handle", None))
                if meta is not None:
                    slo.record_request(
                        meta[0], meta[1], now - r.t_submit, ok=False,
                        tenant=self.session.request_tenant(
                            getattr(r, "handle", None),
                            getattr(r, "tenant", None)))
