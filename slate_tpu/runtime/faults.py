"""Deterministic fault injection + the serving degradation ladder.

SLATE inherits MPI's failure model: a failed rank aborts the job, so
the reference never needs to *decide* anything when hardware misbehaves.
A serving fleet does — and until now every failure path in the runtime
(Executor retry, refine fallback, grouped-bucket degradation,
eviction-under-pressure) could only be exercised by hand-crafted unit
fixtures. This module makes failure a first-class, *reproducible* input:

* :class:`FaultSpec` / :class:`FaultPlan` — a declarative, seeded
  schedule of fault classes (transient dispatch failures, slow-device
  latency, compile stalls, HBM-budget exhaustion, singular/
  non-convergent low-precision operands, dropped fleet snapshots);
* :class:`FaultInjector` — the runtime-side evaluator the Session
  consults at its seams. Decisions are a PURE FUNCTION of
  ``(seed, kind, per-site sequence number)`` (a keyed hash, not a
  shared RNG stream), so two runs that present the same opportunity
  sequence fire the same faults **regardless of thread interleaving**
  — the property ``tools/chaos_serve.py`` exit-gates on
  (``schedule_digest`` equality across same-seed runs);
* the serving-reflex exceptions (:class:`TransientDispatchError`,
  :class:`DeadlineExceeded`, :class:`RequestShed`) raised/failed-into
  futures by the Batcher/Executor reflexes this round adds;
* :data:`DEGRADATION_LADDER` — the declared next-rung-down per serving
  path, promoted from the round-13 ad-hoc ``_serve_small_per_request``
  escape hatch into policy the Executor's circuit breaker walks.

Hot-path discipline (the round-8 tracer rule, extended here by test):
``session.faults`` defaults to ``None`` and every seam guards with ONE
``faults is None`` check — injection disabled costs nothing and calls
nothing in this module.

This module itself is stdlib-only (no jax, no numpy beyond the package
``SlateError`` base): the injector adds no import weight to the
runtime, and the decision math is portable to any driver.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import threading
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

from ..core.exceptions import SlateError

# every fault class the injector can schedule; chaos_serve's acceptance
# requires >= 4 of them enabled simultaneously
KINDS = (
    "dispatch_error",      # transient dispatch failure (flaky tunnel /
                           # interrupted transfer) -> retryable raise
    "slow_device",         # added dispatch latency (a contended or
                           # thermally-throttled chip)
    "compile_stall",       # added latency at the AOT compile seam
    "hbm_exhaustion",      # budget collapses to 0 for one insert ->
                           # eviction-under-pressure
    "lo_factor_fail",      # the low-precision factor comes back
                           # singular -> counted refine fallback
    "refine_no_converge",  # iterative refinement stagnates -> counted
                           # refine fallback
    "snapshot_drop",       # a process snapshot never reaches the fleet
                           # aggregator
    # -- round 17: crash chaos (checkpoint/restore + fleet failover) --
    "process_crash",       # a Session process dies mid-soak -> the
                           # fleet coordinator's failover reflex
    "restore_corrupt",     # a checkpoint blob is corrupted in flight ->
                           # the per-record checksum must catch it and
                           # restore degrades to a counted refactor
                           # (never a wrong answer)
    "replica_stale",       # a replica's resident predates the primary's
                           # state -> counted refresh (evict + refactor
                           # from the registered operand), never served
    # -- round 18: tenant isolation (quotas, fairness, migration) --
    "migration_abort",     # a migration transfer dies mid-flight ->
                           # the source keeps serving untouched and the
                           # coordinator retries, counted — never a
                           # half-resident on the target
    # -- round 20: incremental factor maintenance --
    "update_abort",        # a rank-k update dies mid-apply -> the
                           # resident stays bit-untouched and the verb
                           # degrades to a counted refactor of the
                           # already-committed operand — never a
                           # half-updated factor
)

# seam name -> fault kinds evaluated there. The Session/chaos runner
# consult sites, not kinds, so one seam check covers every class that
# can fire at it.
SITES: Dict[str, Tuple[str, ...]] = {
    "dispatch": ("dispatch_error", "slow_device"),
    "compile": ("compile_stall",),
    "hbm": ("hbm_exhaustion",),
    "refine.lo_factor": ("lo_factor_fail",),
    "refine.converge": ("refine_no_converge",),
    "snapshot": ("snapshot_drop",),
    # round 17: the crash-chaos seams — Session.restore consults
    # "restore" once per checkpoint record; the Fleet coordinator
    # consults "fleet.process" once per soak wave and "fleet.replica"
    # once per replica-served failover handle
    "restore": ("restore_corrupt",),
    "fleet.process": ("process_crash",),
    "fleet.replica": ("replica_stale",),
    # round 18: the Fleet coordinator consults "fleet.migrate" once
    # per migration transfer attempt (HBM-pressure migration — a fired
    # migration_abort kills that attempt mid-flight)
    "fleet.migrate": ("migration_abort",),
    # round 20: Session.update consults "update" once per update verb,
    # BEFORE the resident is touched (abort-before-commit semantics)
    "update": ("update_abort",),
    # round 21: the shadow tuner consults "tuner.compile" once per
    # shadow AOT compile — the stall sleeps there (off the request
    # path, so live solves never feel it) and a transient dispatch
    # failure rejects THAT shadow attempt (breaker-counted), never a
    # live future
    "tuner.compile": ("compile_stall", "dispatch_error"),
}

# The declared degradation ladder (tentpole): when a serving path keeps
# failing (circuit breaker open), this is the next rung down — never a
# wrong answer, always a counted, observable decision. Promoted from
# round 10/13's ad-hoc escapes (``Session._serve_small_per_request``,
# the refine fallback) into policy the Executor walks:
#
#   grouped  -> per_request         one batched program per bucket
#                                   degrades to B independent solves
#                                   (per-item isolation; the round-10
#                                   degraded lane, now breaker-driven)
#   mixed    -> working_precision   refined-from-lo serving demotes to
#                                   a working-precision refactor (the
#                                   round-13 fallback, now also
#                                   breaker-driven)
#   dense    -> per_request         a coalesced dense bucket degrades
#                                   to per-request solves
#   mesh     -> reject              a sharded program has no cheaper
#                                   single-chip form of itself — fail
#                                   fast with a clear error instead of
#                                   retry-storming a sick mesh
DEGRADATION_LADDER: Dict[str, str] = {
    "grouped": "per_request",
    "mixed": "working_precision",
    "dense": "per_request",
    "mesh": "reject",
}


# -- serving-reflex exceptions ----------------------------------------------


class TransientDispatchError(RuntimeError):
    """A retryable dispatch failure (the class the Executor's
    backoff+retry loop covers — deliberately NOT a SlateError, which
    signals a deterministic failure and fails fast)."""


class DeadlineExceeded(SlateError):
    """The request's deadline passed before its solve dispatched; it
    failed fast instead of occupying a batch lane. Deterministic from
    the Executor's point of view: never retried."""


class RequestShed(SlateError):
    """The request was turned away (admission control) or dropped from
    the queue (load shedding) to protect the SLO of the requests that
    stay. Cheapest-to-recompute requests shed first — retrying is
    expected to be cheap for the caller. Never retried server-side."""


class QuotaExceeded(SlateError):
    """The request was turned away at the door because ITS TENANT is
    over one of its declared limits (in-flight cap or flops/s rate —
    runtime/tenancy.TenantPolicy): the round-18 isolation reflex.
    Unlike :class:`RequestShed` (a fleet-health decision that can hit
    anyone), this is the tenant's own quota — other tenants' traffic
    is unaffected and the caller should back off or negotiate a bigger
    quota. Counted in ``quota_rejections_total`` and the conservation
    partition's ``quota_rejected`` outcome — never a silent drop.
    Never retried server-side."""


# -- the plan ----------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One fault class's schedule parameters.

    ``rate`` is the per-opportunity firing probability (evaluated by
    keyed hash — see module docstring). ``after`` skips the first N
    opportunities at the kind's sites (lets a soak warm up cleanly);
    ``count`` caps total firings (None = unlimited); ``latency_s`` is
    the injected sleep for the latency-shaped kinds."""

    kind: str
    rate: float
    latency_s: float = 0.0
    after: int = 0
    count: Optional[int] = None

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"FaultSpec: unknown kind {self.kind!r} "
                             f"(one of {KINDS})")
        if not (0.0 <= self.rate <= 1.0):
            raise ValueError(f"FaultSpec {self.kind}: rate must be in "
                             f"[0, 1], got {self.rate}")

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """A seed plus the fault classes to schedule under it. Immutable
    and JSON-serializable, so a chaos artifact can embed the exact
    plan that produced it and a rerun can replay it verbatim."""

    seed: int
    specs: Tuple[FaultSpec, ...]

    def __post_init__(self):
        kinds = [s.kind for s in self.specs]
        if len(set(kinds)) != len(kinds):
            raise ValueError(f"FaultPlan: duplicate kinds in {kinds}")

    @property
    def kinds(self) -> Tuple[str, ...]:
        return tuple(s.kind for s in self.specs)

    def to_dict(self) -> dict:
        return {"seed": self.seed,
                "specs": [s.to_dict() for s in self.specs]}

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        return cls(seed=int(d["seed"]),
                   specs=tuple(FaultSpec(**s) for s in d["specs"]))


def _unit(seed: int, stream: str, seq: int) -> float:
    """Deterministic uniform in [0, 1) keyed by (seed, stream, seq) —
    a keyed hash, not an RNG stream, so one site's draw count never
    shifts another site's decisions (the schedule-reproducibility
    property chaos_serve gates on)."""
    h = hashlib.blake2b(f"{seed}:{stream}:{seq}".encode(),
                        digest_size=8).digest()
    return int.from_bytes(h, "big") / 2.0 ** 64


class FaultInjector:
    """Runtime evaluator of a :class:`FaultPlan`.

    The serving seams call :meth:`fire` with their site name; every
    spec mapped to that site is evaluated against the site's own
    monotone opportunity counter. Fired decisions are appended to
    ``self.log`` — the deterministic fault schedule; two injectors
    built from the same plan and presented the same per-site
    opportunity sequences produce identical logs (pinned by test and
    exit-gated by chaos_serve via :meth:`schedule_digest`).

    Thread-safe: one lock around counter reads/bumps; decisions
    themselves are pure functions of (seed, kind, seq).
    """

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._by_site: Dict[str, Tuple[FaultSpec, ...]] = {
            site: tuple(s for s in plan.specs if s.kind in kinds)
            for site, kinds in SITES.items()}
        # incident hook (obs/recorder.py): a firing is an anomaly
        # worth black-box capture; None = one is-None check
        self.recorder = None
        self._lock = threading.Lock()
        self._seq: Dict[str, int] = defaultdict(int)
        self._fired: Dict[str, int] = defaultdict(int)
        # the schedule: (site, kind, site-sequence) per firing, in
        # firing order
        self.log: List[Tuple[str, str, int]] = []

    # -- the seam call ------------------------------------------------------

    def fire(self, site: str) -> Tuple[FaultSpec, ...]:
        """One opportunity at ``site``: bump the site counter and
        return the specs that fire at this sequence number (possibly
        empty). The caller applies the effects (sleep / raise / budget
        collapse) — the injector only decides."""
        specs = self._by_site.get(site)
        if not specs:
            with self._lock:
                self._seq[site] += 1
            return ()
        fired = []
        with self._lock:
            seq = self._seq[site]
            self._seq[site] = seq + 1
            for spec in specs:
                if seq < spec.after:
                    continue
                if spec.count is not None \
                        and self._fired[spec.kind] >= spec.count:
                    continue
                if _unit(self.plan.seed, spec.kind, seq) < spec.rate:
                    self._fired[spec.kind] += 1
                    self.log.append((site, spec.kind, seq))
                    fired.append(spec)
        rec = self.recorder
        if rec is not None and fired:
            # outside the lock: incident providers walk session state
            rec.incident("fault", key=site,
                         context={"site": site,
                                  "kinds": [s.kind for s in fired]})
        return tuple(fired)

    def hook(self, site: str):
        """A zero-arg bool callable for seams that take a plug-in hook
        (refine/engine's ``drive(..., fault_hook=...)``)."""
        return lambda: bool(self.fire(site))

    def uniform(self, stream: str) -> float:
        """Deterministic jitter draw (the Executor's backoff jitter
        uses this when an injector is attached, so a chaos run's retry
        timing is reproducible too)."""
        with self._lock:
            seq = self._seq[f"uniform:{stream}"]
            self._seq[f"uniform:{stream}"] = seq + 1
        return _unit(self.plan.seed, f"uniform:{stream}", seq)

    # -- the schedule -------------------------------------------------------

    def fired_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._fired)

    def opportunity_counts(self) -> Dict[str, int]:
        with self._lock:
            return {k: v for k, v in self._seq.items()
                    if not k.startswith("uniform:")}

    def schedule(self) -> List[Tuple[str, str, int]]:
        with self._lock:
            return list(self.log)

    def schedule_digest(self) -> str:
        """Stable digest of the fault schedule — the reproducibility
        token chaos_serve compares across same-seed runs and stamps
        into the committed artifact."""
        payload = json.dumps(self.schedule(), separators=(",", ":"))
        return "sha256:" + hashlib.sha256(payload.encode()).hexdigest()


def default_plan(seed: int = 1) -> FaultPlan:
    """The chaos-soak default: every injectable class enabled at rates
    tuned so a few-hundred-request soak exercises each reflex at least
    once while most traffic still completes (the invariants need both
    populations)."""
    return FaultPlan(seed=seed, specs=(
        FaultSpec("dispatch_error", rate=0.12),
        FaultSpec("slow_device", rate=0.10, latency_s=2e-3),
        FaultSpec("compile_stall", rate=0.5, latency_s=5e-3),
        FaultSpec("hbm_exhaustion", rate=0.10),
        FaultSpec("lo_factor_fail", rate=1.0, count=1),
        FaultSpec("refine_no_converge", rate=1.0, count=1),
        FaultSpec("snapshot_drop", rate=1.0, count=1),
    ))
