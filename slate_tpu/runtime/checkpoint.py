"""Versioned checkpoint/restore of a Session's resident state.

SLATE inherits MPI's abort-on-failure semantics: the reference runtime
has no rank-loss recovery — a lost rank kills the job and every
factorization it held. A serving fleet cannot afford that: a crashed
Session process must not silently lose every resident factor (hours of
amortized factorization work) and force a refactor storm onto the
survivors. This module makes the resident state a durable, portable
artifact:

* ``save_session(session, path)`` writes a **versioned checkpoint
  directory**: a stdlib-readable ``manifest.json``
  (:data:`CHECKPOINT_SCHEMA`) plus one raw-bytes blob per array leaf,
  each with its own sha256 **checksum** — one record per RESIDENT
  factor carrying the factor tree AND the full operator metadata (op,
  m/n, working dtype, nb, band, refine policy, tenant, mesh spec,
  factorization info, handle heat, numerical-health state);
* ``restore_session(session, path)`` **re-registers** each record's
  operator and re-inserts its factor WITHOUT refactoring (warm
  restart): the restored payload is the byte-identical factor tree, so
  a restored handle's solve is bit-identical to the pre-checkpoint
  resident's solve (pinned for dense, small-bucket, and refined-bf16
  entries; mesh residents restore **re-sharded onto the current
  grid** — bit-identity is not claimed across placements, the round-11
  rule). Heat, health, and tenant attribution carry over.

**Corruption is detected, never served.** Every blob read verifies
length + sha256; a mismatched payload blob degrades that record to
refactor-on-miss (counted in ``restore_corrupt_total``, warned) — the
operator still registers, so serving continues with a refactor instead
of a wrong answer. The ``restore_corrupt`` fault class
(runtime/faults.py) injects exactly this at the ``"restore"`` seam so
``tools/chaos_serve.py`` can exit-gate the reflex deterministically.

The manifest is deliberately **jax-free JSON**: ``tools/bench_gate.py``
carries a mirror validator (``validate_checkpoint_manifest``, the
placement-schema duplication discipline — tests pin the mirrors equal)
so CI can hold a committed or drill-produced checkpoint to the schema
without importing the runtime.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shutil
import time
from typing import Hashable, List, Optional, Tuple

import numpy as np

from ..core.exceptions import SlateError
from ..core.grid import ProcessGrid
from ..core.tiled_matrix import TiledMatrix
from ..core.types import Diag, MatrixKind, Op, Uplo
from ..linalg.band_packed import PackedBand
from ..linalg.qr import QRFactors
from ..obs.tracing import log as _obs_log
from ..refine.policy import RefinePolicy
# direct module import (not the spectral package __init__, which pulls
# the staged pipeline drivers) — checkpoint only needs the pytree types
from ..spectral.types import EigFactors, SVDFactors

CHECKPOINT_SCHEMA = "slate_tpu.checkpoint.v1"
# round 20: delta checkpoints — same record structure, but any blob
# whose sha256 already exists in a BASE checkpoint is referenced
# (``"base": true`` on its descriptor) instead of rewritten, so
# replicating an incrementally-updated resident ships only the blobs
# the update actually changed (an appended-QR update leaves the base
# factor blobs byte-identical; a chol update rewrites only L)
DELTA_SCHEMA = "slate_tpu.checkpoint.delta.v1"
# every key a checkpoint record carries. Mirrored (deliberately, the
# bench_gate/placement duplication pattern: tools/bench_gate.py stays
# importable without package context) as
# bench_gate.CHECKPOINT_RECORD_KEYS; tests pin the two tuples equal.
CHECKPOINT_RECORD_KEYS = (
    "handle", "handle_type", "op", "m", "n", "band", "dtype", "nb",
    "tenant", "refine", "mesh", "info", "heat", "last_access",
    "health", "operator", "payload")
# every key a blob descriptor carries (mirrored alongside)
CHECKPOINT_BLOB_KEYS = ("blob", "shape", "dtype", "nbytes", "sha256")
MANIFEST_NAME = "manifest.json"
BLOBS_DIR = "blobs"


class CheckpointCorrupt(SlateError):
    """A blob failed its length/sha256 check — the record's factor is
    not trustworthy and must not serve (degrade to refactor)."""


def _np_dtype(name: str) -> np.dtype:
    """Canonical dtype name -> numpy dtype; bfloat16 resolves through
    ml_dtypes (``np.dtype("bfloat16")`` raises TypeError)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


class _BlobWriter:
    """Writes array leaves as raw-bytes blob files + checksum descs."""

    def __init__(self, blob_dir: str):
        self.blob_dir = blob_dir
        self.count = 0

    def add(self, arr) -> dict:
        # np.asarray gathers a sharded jax array to the host — the
        # checkpoint is placement-independent by construction (restore
        # re-shards onto the CURRENT grid)
        a = np.ascontiguousarray(np.asarray(arr))
        raw = a.tobytes()
        bid = f"b{self.count:05d}.bin"
        self.count += 1
        with open(os.path.join(self.blob_dir, bid), "wb") as f:
            f.write(raw)
        return {
            "blob": bid,
            "shape": [int(d) for d in a.shape],
            "dtype": str(a.dtype.name),
            "nbytes": len(raw),
            "sha256": hashlib.sha256(raw).hexdigest(),
        }


class _DeltaBlobWriter(_BlobWriter):
    """Blob-level dedup against a BASE checkpoint (round 20): a leaf
    whose raw bytes hash to a sha256 the base already holds is
    referenced (``"base": true``, the base's blob id) instead of
    rewritten — the per-blob checksums the v1 format already carries
    ARE the diff index, so the delta needs no new hashing scheme."""

    def __init__(self, blob_dir: str, base_index: dict):
        super().__init__(blob_dir)
        self.base_index = base_index  # sha256 -> base blob descriptor
        self.reused = 0
        self.written_bytes = 0
        self.total_bytes = 0

    def add(self, arr) -> dict:
        a = np.ascontiguousarray(np.asarray(arr))
        raw = a.tobytes()
        self.total_bytes += len(raw)
        sha = hashlib.sha256(raw).hexdigest()
        base = self.base_index.get(sha)
        if base is not None and int(base["nbytes"]) == len(raw):
            self.reused += 1
            return {"blob": base["blob"],
                    "shape": [int(d) for d in a.shape],
                    "dtype": str(a.dtype.name), "nbytes": len(raw),
                    "sha256": sha, "base": True}
        bid = f"b{self.count:05d}.bin"
        self.count += 1
        self.written_bytes += len(raw)
        with open(os.path.join(self.blob_dir, bid), "wb") as f:
            f.write(raw)
        return {"blob": bid, "shape": [int(d) for d in a.shape],
                "dtype": str(a.dtype.name), "nbytes": len(raw),
                "sha256": sha}


class _BlobReader:
    """Reads blob files back, verifying length + sha256 per blob.
    ``base_dir``: where ``"base": true`` descriptors resolve (delta
    checkpoints — round 20); None for a full checkpoint.

    ``corrupt_next``: the deterministic ``restore_corrupt`` fault hook —
    the NEXT read's bytes are flipped before verification, so the
    checksum must catch the injected corruption exactly like a real
    torn write would be caught."""

    def __init__(self, blob_dir: str, base_dir: Optional[str] = None):
        self.blob_dir = blob_dir
        self.base_dir = base_dir
        self.corrupt_next = False

    def read(self, desc: dict) -> np.ndarray:
        d = (self.base_dir if desc.get("base") and self.base_dir
             else self.blob_dir)
        path = os.path.join(d, str(desc["blob"]))
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError as e:
            raise CheckpointCorrupt(f"checkpoint blob {desc['blob']!r} "
                                    f"unreadable: {e}")
        if self.corrupt_next:
            self.corrupt_next = False
            raw = (bytes([raw[0] ^ 0xFF]) + raw[1:]) if raw else b"\xff"
        if len(raw) != int(desc["nbytes"]) \
                or hashlib.sha256(raw).hexdigest() != desc["sha256"]:
            raise CheckpointCorrupt(
                f"checkpoint blob {desc['blob']!r} failed its checksum "
                "(corrupt or truncated)")
        a = np.frombuffer(raw, dtype=_np_dtype(str(desc["dtype"])))
        return a.reshape([int(d) for d in desc["shape"]]).copy()


# -- factor-tree (de)serialization -------------------------------------------


def _encode_node(node, w: _BlobWriter) -> dict:
    """One payload/operator tree node -> a JSON descriptor + blobs.
    Covers every type a Session resident can hold: TiledMatrix,
    PackedBand, QRFactors, plain arrays, and nested tuples/lists."""
    if isinstance(node, TiledMatrix):
        return {
            "type": "tiled", "m": int(node.m), "n": int(node.n),
            "nb": int(node.nb), "kind": node.kind.name,
            "uplo": node.uplo.name, "op": node.op.name,
            "diag": node.diag.name, "kl": int(node.kl),
            "ku": int(node.ku), "cyclic": bool(node.cyclic),
            "packing": str(node.packing), "data": w.add(node.data),
        }
    if isinstance(node, PackedBand):
        return {"type": "packed_band", "n": int(node.n),
                "kl": int(node.kl), "ku": int(node.ku),
                "hermitian": bool(node.hermitian), "ab": w.add(node.ab)}
    if isinstance(node, QRFactors):
        return {"type": "qr_factors", "m": int(node.m), "n": int(node.n),
                "nb": int(node.nb), "vr": w.add(node.vr),
                "t": w.add(node.t)}
    if isinstance(node, EigFactors):
        # round-19 spectral residents: the eigenvector TiledMatrix
        # nests as its own node (placement metadata and all), the
        # spectrum is a plain blob
        return {"type": "eig_factors", "v": _encode_node(node.v, w),
                "lam": w.add(node.lam)}
    if isinstance(node, SVDFactors):
        return {"type": "svd_factors", "u": _encode_node(node.u, w),
                "s": w.add(node.s), "v": _encode_node(node.v, w)}
    if isinstance(node, (tuple, list)):
        return {"type": "tuple",
                "items": [_encode_node(x, w) for x in node]}
    if hasattr(node, "shape") and hasattr(node, "dtype"):
        return {"type": "array", "a": w.add(node)}
    raise SlateError(f"checkpoint: unsupported payload node type "
                     f"{type(node).__name__}")


def _decode_node(desc: dict, r: _BlobReader, device: bool = True):
    """Inverse of :func:`_encode_node`. ``device=False`` keeps plain
    arrays host-side (small-op operators are stored as np arrays)."""
    import jax.numpy as jnp
    t = desc["type"]
    if t == "tuple":
        return tuple(_decode_node(d, r, device) for d in desc["items"])
    if t == "array":
        a = r.read(desc["a"])
        return jnp.asarray(a) if device else a
    if t == "tiled":
        data = jnp.asarray(r.read(desc["data"]))
        return TiledMatrix(
            data, int(desc["m"]), int(desc["n"]), int(desc["nb"]),
            MatrixKind[desc["kind"]], Uplo[desc["uplo"]],
            Op[desc["op"]], Diag[desc["diag"]], int(desc["kl"]),
            int(desc["ku"]), grid=None, cyclic=bool(desc["cyclic"]),
            packing=str(desc["packing"]))
    if t == "packed_band":
        return PackedBand(jnp.asarray(r.read(desc["ab"])),
                          int(desc["n"]), int(desc["kl"]),
                          int(desc["ku"]), bool(desc["hermitian"]))
    if t == "qr_factors":
        return QRFactors(jnp.asarray(r.read(desc["vr"])),
                         jnp.asarray(r.read(desc["t"])),
                         int(desc["m"]), int(desc["n"]), int(desc["nb"]))
    if t == "eig_factors":
        return EigFactors(_decode_node(desc["v"], r, device),
                          jnp.asarray(r.read(desc["lam"])))
    if t == "svd_factors":
        return SVDFactors(_decode_node(desc["u"], r, device),
                          jnp.asarray(r.read(desc["s"])),
                          _decode_node(desc["v"], r, device))
    raise CheckpointCorrupt(f"checkpoint: unknown node type {t!r}")


def _reshard_node(node, grid: ProcessGrid):
    """Re-shard a restored payload's TiledMatrix leaves onto ``grid``
    (the restoring session's mesh — the round-11 rule: a mesh resident
    restores onto the CURRENT placement; bit-identity is not claimed
    across placements)."""
    if isinstance(node, TiledMatrix):
        return node.shard(grid)
    if isinstance(node, EigFactors):
        import jax
        return EigFactors(_reshard_node(node.v, grid),
                          jax.device_put(node.lam, grid.replicated()))
    if isinstance(node, SVDFactors):
        import jax
        return SVDFactors(_reshard_node(node.u, grid),
                          jax.device_put(node.s, grid.replicated()),
                          _reshard_node(node.v, grid))
    if isinstance(node, tuple):
        return tuple(_reshard_node(x, grid) for x in node)
    return node


# -- manifest validation ------------------------------------------------------


def validate_manifest(doc, schema: str = CHECKPOINT_SCHEMA
                      ) -> List[str]:
    """Schema errors for a checkpoint manifest (empty list = valid).
    ``schema`` selects the expected flavor: the full v1 format
    (default) or the round-20 delta format (same records, plus the
    ``base_blobs`` generation pointer its reused blob ids resolve in).
    The producer self-checks its own output (the placement-snapshot
    discipline); ``tools/bench_gate.py`` mirrors this jax-free so CI
    can validate a manifest without the runtime (mirror-pinned)."""
    errs: List[str] = []
    if not isinstance(doc, dict):
        return ["checkpoint manifest is not an object"]
    if schema not in (CHECKPOINT_SCHEMA, DELTA_SCHEMA):
        return [f"unknown checkpoint schema {schema!r}"]
    if doc.get("schema") != schema:
        errs.append(f"schema != {schema!r}")
    if schema == DELTA_SCHEMA and (
            not isinstance(doc.get("base_blobs"), str)
            or not doc.get("base_blobs")):
        errs.append("base_blobs missing/not a string")
    if not isinstance(doc.get("host"), str) or not doc.get("host"):
        errs.append("host missing/not a string")
    ga = doc.get("generated_at")
    if not isinstance(ga, (int, float)) or isinstance(ga, bool):
        errs.append("generated_at missing/not a number")
    records = doc.get("records")
    if not isinstance(records, list):
        return errs + ["records missing/not a list"]
    for i, rec in enumerate(records):
        if not isinstance(rec, dict):
            errs.append(f"records[{i}]: not an object")
            continue
        for k in CHECKPOINT_RECORD_KEYS:
            if k not in rec:
                errs.append(f"records[{i}]: missing {k!r}")
        if rec.get("handle_type") not in ("str", "int"):
            errs.append(f"records[{i}].handle_type: not 'str'/'int'")
        for k in ("op", "dtype"):
            if k in rec and not isinstance(rec[k], str):
                errs.append(f"records[{i}].{k}: not a string")
        for k in ("m", "n", "band", "nb", "info"):
            v = rec.get(k)
            if v is not None and (not isinstance(v, int)
                                  or isinstance(v, bool)):
                errs.append(f"records[{i}].{k}: not an int")
        mesh = rec.get("mesh")
        if mesh is not None and (not isinstance(mesh, list)
                                 or len(mesh) != 2):
            errs.append(f"records[{i}].mesh: not [p, q] or null")
        for k in ("operator", "payload"):
            errs.extend(_validate_node(rec.get(k), f"records[{i}].{k}"))
    return errs


def _validate_node(desc, where: str) -> List[str]:
    if not isinstance(desc, dict) or "type" not in desc:
        return [f"{where}: not a node descriptor"]
    t = desc["type"]
    if t == "tuple":
        items = desc.get("items")
        if not isinstance(items, list):
            return [f"{where}.items: missing/not a list"]
        errs = []
        for j, d in enumerate(items):
            errs.extend(_validate_node(d, f"{where}[{j}]"))
        return errs
    if t in ("eig_factors", "svd_factors"):
        # round-19 spectral nodes: basis matrices nest as full node
        # descriptors, the spectrum is a direct blob
        nested = ("v",) if t == "eig_factors" else ("u", "v")
        spec = "lam" if t == "eig_factors" else "s"
        errs = []
        for field in nested:
            errs.extend(_validate_node(desc.get(field),
                                       f"{where}.{field}"))
        b = desc.get(spec)
        if not isinstance(b, dict):
            errs.append(f"{where}.{spec}: missing blob descriptor")
        else:
            for k in CHECKPOINT_BLOB_KEYS:
                if k not in b:
                    errs.append(f"{where}.{spec}: blob missing {k!r}")
        return errs
    blob_fields = {"array": ("a",), "tiled": ("data",),
                   "packed_band": ("ab",), "qr_factors": ("vr", "t")}
    if t not in blob_fields:
        return [f"{where}.type: unknown {t!r}"]
    errs = []
    for field in blob_fields[t]:
        b = desc.get(field)
        if not isinstance(b, dict):
            errs.append(f"{where}.{field}: missing blob descriptor")
            continue
        for k in CHECKPOINT_BLOB_KEYS:
            if k not in b:
                errs.append(f"{where}.{field}: blob missing {k!r}")
    return errs


# -- save / restore -----------------------------------------------------------


def _new_generation(path: str) -> Tuple[List[str], str, str]:
    """Crash-safety primitive shared by full and delta saves: blobs go
    into a FRESH generation directory, and the manifest (replaced
    atomically, last) is what points at it — a death mid-save leaves
    the previous manifest still naming the previous generation's
    intact blobs, so the crash a checkpoint exists to survive can
    never corrupt the only durable copy. Returns (prior generation
    dirs, new blobs dir name, new blobs dir path)."""
    os.makedirs(path, exist_ok=True)
    prior = [d for d in os.listdir(path)
             if d == BLOBS_DIR or d.startswith(BLOBS_DIR + "-")]
    gen = 0
    for d in prior:
        try:
            gen = max(gen, int(d.rsplit("-", 1)[1]) + 1)
        except (IndexError, ValueError):
            gen = max(gen, 1)  # legacy unsuffixed "blobs"
    blobs_name = f"{BLOBS_DIR}-{gen:05d}"
    blob_dir = os.path.join(path, blobs_name)
    os.makedirs(blob_dir, exist_ok=True)
    return prior, blobs_name, blob_dir


def _snapshot_residents(session, only: Optional[List[Hashable]]):
    """Snapshot the resident references under the lock, then gather/
    hash/write OUTSIDE it — a checkpoint of hundreds of MB must not
    stop-the-world the serving threads for its disk I/O. Entries and
    payload trees are immutable once cached; a concurrent evict just
    means the checkpoint keeps a resident the cache no longer does
    (a snapshot, not a transaction)."""
    keep = None if only is None else set(only)
    with session._lock:
        return [(h, session._ops[h], res)
                for h, res in session._cache.items()
                if (keep is None or h in keep)
                and session._ops.get(h) is not None]


def _publish_manifest(session, path: str, manifest: dict,
                      prior: List[str], blobs_name: str, skipped: int):
    """Self-check, atomic manifest replace, prune superseded
    generations — the shared tail of full and delta saves."""
    errs = validate_manifest(manifest,
                             schema=str(manifest.get("schema")))
    if errs:
        raise SlateError(f"checkpoint: manifest self-check failed "
                         f"({errs[:3]})")
    tmp = os.path.join(path, MANIFEST_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, os.path.join(path, MANIFEST_NAME))
    for d in prior:  # superseded generations, pruned post-publish
        if d != blobs_name:
            shutil.rmtree(os.path.join(path, d), ignore_errors=True)
    session.metrics.inc("checkpoints_written_total")
    session.metrics.inc("checkpoint_records_total",
                        len(manifest["records"]))
    if skipped:
        session.metrics.inc("checkpoint_skipped_handles", skipped)


def _default_host(host: Optional[str]) -> str:
    if host is None:
        import socket as _socket
        host = f"{_socket.gethostname()}:{os.getpid()}"
    return host


def save_session(session, path: str,
                 only: Optional[List[Hashable]] = None,
                 host: Optional[str] = None) -> dict:
    """Write ``session``'s resident state to checkpoint directory
    ``path`` (created; an existing checkpoint there is overwritten).
    One record per RESIDENT factor — registered-but-uncached operators
    carry no expensive state and are deliberately not checkpointed
    (the fleet retains their registration specs; refactor-on-miss is
    their recovery path). ``only`` filters to a handle subset (the
    fleet's replication transfer). Returns the manifest."""
    host = _default_host(host)
    prior, blobs_name, blob_dir = _new_generation(path)
    writer = _BlobWriter(blob_dir)
    items = _snapshot_residents(session, only)
    records, skipped = _gather_records(session, writer, items)
    manifest = {
        "schema": CHECKPOINT_SCHEMA,
        "host": host,
        "generated_at": time.time(),
        "blobs": blobs_name,
        "records": records,
    }
    _publish_manifest(session, path, manifest, prior, blobs_name,
                      skipped)
    return manifest


def _gather_records(session, writer: _BlobWriter, items
                    ) -> Tuple[list, int]:
    """One manifest record per snapshotted resident (shared by the
    full and delta writers — the writer decides what hits disk)."""
    attr = session.attribution
    nm = session.numerics
    records = []
    skipped = 0
    for h, entry, res in items:
        if not isinstance(h, (str, int)) or isinstance(h, bool):
            # restorable handles must round-trip through JSON; an
            # arbitrary hashable cannot — counted, never silent
            skipped += 1
            _obs_log.warning(
                "checkpoint: handle %r is not JSON-representable "
                "(str/int); its resident is skipped", h)
            continue
        try:
            oper = _encode_node(entry.A, writer)
            payload = _encode_node(res.payload, writer)
        except SlateError as e:
            skipped += 1
            _obs_log.warning("checkpoint: handle %r skipped (%s)",
                             h, e)
            continue
        heat, last = 0.0, None
        if attr is not None:
            hrow = attr.export_heat(h)
            if hrow is not None:
                heat, last = hrow["heat"], hrow["last_access"]
        A = entry.A
        dtype = A.ab.dtype if isinstance(A, PackedBand) else A.dtype
        records.append({
            "handle": h,
            "handle_type": "int" if isinstance(h, int) else "str",
            "op": entry.op, "m": int(entry.m), "n": int(entry.n),
            "band": int(entry.band),
            "dtype": str(np.dtype(dtype).name)
            if not _is_bf16(dtype) else "bfloat16",
            "nb": int(getattr(A, "nb", 0) or 0),
            "tenant": entry.tenant,
            "refine": (None if entry.refine is None
                       else dataclasses.asdict(entry.refine)),
            "mesh": (None if entry.grid is None
                     else [int(entry.grid.p), int(entry.grid.q)]),
            "info": int(res.info),
            "heat": float(heat),
            "last_access": last,
            "health": (None if nm is None
                       else nm.export_state(h)),
            "operator": oper,
            "payload": payload,
        })
    return records, skipped


# -- delta checkpoints (round 20: replicate updates, not factors) ------------


def _iter_blob_descs(desc):
    """Every blob descriptor reachable from a node descriptor (the
    index the delta writer dedups against)."""
    if not isinstance(desc, dict):
        return
    t = desc.get("type")
    if t == "tuple":
        for d in desc.get("items", []):
            yield from _iter_blob_descs(d)
    elif t == "eig_factors":
        yield from _iter_blob_descs(desc.get("v"))
        yield desc.get("lam")
    elif t == "svd_factors":
        yield from _iter_blob_descs(desc.get("u"))
        yield desc.get("s")
        yield from _iter_blob_descs(desc.get("v"))
    elif t == "array":
        yield desc.get("a")
    elif t == "tiled":
        yield desc.get("data")
    elif t == "packed_band":
        yield desc.get("ab")
    elif t == "qr_factors":
        yield desc.get("vr")
        yield desc.get("t")


def _base_blob_index(base_manifest: dict) -> dict:
    """sha256 -> blob descriptor over every blob a base checkpoint
    holds. Only non-delta descriptors index (a blob the base itself
    borrowed lives elsewhere and cannot be referenced)."""
    index = {}
    for rec in base_manifest.get("records", []):
        for key in ("operator", "payload"):
            for b in _iter_blob_descs(rec.get(key)):
                if isinstance(b, dict) and not b.get("base") \
                        and "sha256" in b:
                    index[str(b["sha256"])] = b
    return index


def save_session_delta(session, path: str, base_manifest: dict,
                       only: Optional[List[Hashable]] = None,
                       host: Optional[str] = None
                       ) -> Tuple[dict, dict]:
    """Delta checkpoint of ``session`` against ``base_manifest`` (a
    previously written FULL checkpoint's manifest): same record
    structure, but blobs whose sha256 the base already holds are
    referenced instead of rewritten — so replicating an incrementally
    updated resident ships only what the update changed (for an
    appended-QR resident that is the append block, never the base
    factor). Returns ``(manifest, stats)`` with stats =
    ``{"sync_bytes", "full_bytes", "reused_blobs", "written_blobs"}``
    (sync_bytes counts the manifest too — it IS part of the wire
    transfer). The restore side needs BOTH directories:
    :func:`restore_session_delta`."""
    if str(base_manifest.get("schema")) != CHECKPOINT_SCHEMA:
        raise SlateError("checkpoint: delta base must be a full "
                         f"{CHECKPOINT_SCHEMA!r} checkpoint")
    host = _default_host(host)
    prior, blobs_name, blob_dir = _new_generation(path)
    writer = _DeltaBlobWriter(blob_dir, _base_blob_index(base_manifest))
    items = _snapshot_residents(session, only)
    records, skipped = _gather_records(session, writer, items)
    manifest = {
        "schema": DELTA_SCHEMA,
        "host": host,
        "generated_at": time.time(),
        "blobs": blobs_name,
        # the base GENERATION the reused blob ids resolve in — the
        # retainer must keep that base directory unchanged (the
        # fleet keeps one per replica edge)
        "base_blobs": str(base_manifest.get("blobs", BLOBS_DIR)),
        "base_host": str(base_manifest.get("host", "")),
        "records": records,
    }
    _publish_manifest(session, path, manifest, prior, blobs_name,
                      skipped)
    manifest_bytes = os.path.getsize(os.path.join(path, MANIFEST_NAME))
    stats = {
        "sync_bytes": int(writer.written_bytes) + int(manifest_bytes),
        "full_bytes": int(writer.total_bytes) + int(manifest_bytes),
        "reused_blobs": int(writer.reused),
        "written_blobs": int(writer.count),
    }
    session.metrics.inc("delta_checkpoints_written_total")
    session.metrics.inc("delta_sync_bytes", stats["sync_bytes"])
    session.metrics.inc("delta_full_bytes", stats["full_bytes"])
    return manifest, stats


def _is_bf16(dtype) -> bool:
    return str(dtype) == "bfloat16"


def load_manifest(path: str,
                  schema: str = CHECKPOINT_SCHEMA) -> dict:
    """Read + schema-validate a checkpoint directory's manifest
    (``schema``: the expected flavor — full by default, DELTA_SCHEMA
    for a delta directory)."""
    mpath = os.path.join(path, MANIFEST_NAME)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise SlateError(f"checkpoint: manifest unreadable at "
                         f"{mpath!r} ({e})")
    errs = validate_manifest(manifest, schema=schema)
    if errs:
        raise SlateError(f"checkpoint: invalid manifest at {mpath!r} "
                         f"({errs[:3]})")
    return manifest


def restore_session(session, path: str,
                    only: Optional[List[Hashable]] = None,
                    manifest: Optional[dict] = None) -> dict:
    """Restore a checkpoint into ``session``: re-register each record's
    operator and re-insert its factor WITHOUT refactoring. Returns a
    summary ``{"registered": [...], "restored": [...], "corrupt":
    [...], "conflicts": [...], "skipped": [...]}``.

    Degradation rules (never a wrong answer):
    * payload blob fails its checksum -> the operator still registers
      but the factor is NOT cached (refactor-on-miss; counted in
      ``restore_corrupt_total``);
    * operator blob fails its checksum -> the record cannot serve at
      all and is skipped (counted, warned);
    * handle already registered -> the record is skipped as a conflict
      (the live operator wins — a restore must never clobber serving
      state).

    Mesh records re-shard onto the restoring session's grid (or a
    fresh grid of the recorded [p, q] shape when the session has
    none). Heat/health/tenant carry over when the restoring session
    has an attribution ledger / numerics monitor attached.

    ``manifest``: an already-loaded (validated) manifest for ``path``
    — the fleet's failover loads it ONCE and threads it through its
    per-handle restores instead of re-parsing per handle."""
    if manifest is None:
        manifest = load_manifest(path)
    blob_dir = os.path.join(path, str(manifest.get("blobs", BLOBS_DIR)))
    return _restore_records(session, manifest, blob_dir, None, only)


def restore_session_delta(session, path: str, base_path: str,
                          only: Optional[List[Hashable]] = None,
                          manifest: Optional[dict] = None) -> dict:
    """Restore a DELTA checkpoint (round 20): records read exactly
    like :func:`restore_session`, but blob descriptors marked
    ``"base": true`` resolve in ``base_path``'s recorded blob
    generation — the receiver already holds those bytes from the full
    checkpoint it retained, so the wire transfer was the delta
    directory alone. Every blob (reused or shipped) still verifies
    length + sha256; the degradation rules are unchanged."""
    if manifest is None:
        manifest = load_manifest(path, schema=DELTA_SCHEMA)
    blob_dir = os.path.join(path, str(manifest.get("blobs", BLOBS_DIR)))
    base_dir = os.path.join(base_path,
                            str(manifest.get("base_blobs", BLOBS_DIR)))
    session.metrics.inc("delta_restores_total")
    return _restore_records(session, manifest, blob_dir, base_dir,
                            only)


def _restore_records(session, manifest: dict, blob_dir: str,
                     base_dir: Optional[str],
                     only: Optional[List[Hashable]]) -> dict:
    """The shared restore loop (full and delta checkpoints differ only
    in where a blob descriptor's bytes live)."""
    from .session import SMALL_OPS, _Resident, _tree_nbytes
    keep = None if only is None else set(only)
    summary = {"registered": [], "restored": [], "corrupt": [],
               "conflicts": [], "skipped": []}
    for rec in manifest["records"]:
        h = int(rec["handle"]) if rec["handle_type"] == "int" \
            else str(rec["handle"])
        if keep is not None and h not in keep:
            continue
        session.metrics.inc("restore_records_total")
        if h in session:
            session.metrics.inc("restore_conflicts_total")
            summary["conflicts"].append(h)
            continue
        # one fault opportunity per processed record — the injected
        # restore_corrupt flips a payload byte BEFORE verification, so
        # the checksum must catch it (the chaos exit gate)
        corrupt_injected = False
        if session.faults is not None:
            fired = session._fault("restore")
            corrupt_injected = any(s.kind == "restore_corrupt"
                                   for s in fired)
        reader = _BlobReader(blob_dir, base_dir)
        small = rec["op"] in SMALL_OPS  # host-side operators
        try:
            A = _decode_node(rec["operator"], reader, device=not small)
        except CheckpointCorrupt as e:
            session.metrics.inc("restore_corrupt_total")
            _obs_log.warning(
                "restore: operator of %r is corrupt (%s); record "
                "skipped — the handle cannot serve from this "
                "checkpoint", h, e)
            summary["skipped"].append(h)
            continue
        mesh = None
        if rec["mesh"] is not None:
            mesh = session.grid
            if mesh is None:
                try:
                    mesh = ProcessGrid.create(int(rec["mesh"][0]),
                                              int(rec["mesh"][1]))
                except ValueError as e:
                    _obs_log.warning(
                        "restore: mesh record %r needs a %sx%s grid "
                        "this process cannot build (%s); skipped", h,
                        rec["mesh"][0], rec["mesh"][1], e)
                    summary["skipped"].append(h)
                    continue
        policy = (None if rec["refine"] is None
                  else RefinePolicy(**rec["refine"]))
        try:
            session.register(A, op=rec["op"], handle=h, refine=policy,
                             tenant=rec["tenant"], mesh=mesh)
        except SlateError as e:
            _obs_log.warning("restore: register of %r failed (%s); "
                             "record skipped", h, e)
            summary["skipped"].append(h)
            continue
        summary["registered"].append(h)
        reader.corrupt_next = corrupt_injected
        try:
            payload = _decode_node(rec["payload"], reader)
        except CheckpointCorrupt as e:
            # THE degradation rule: checksum caught it, the factor is
            # not served — the operator stays registered and the next
            # solve refactors (counted refactor-on-miss), never a
            # wrong answer from corrupt bits
            session.metrics.inc("restore_corrupt_total")
            _obs_log.warning(
                "restore: factor of %r is corrupt (%s); degrading to "
                "refactor-on-miss", h, e)
            summary["corrupt"].append(h)
            continue
        with session._lock:
            entry = session._ops.get(h)
            if entry is None:  # raced unregister
                summary["skipped"].append(h)
                continue
            if entry.grid is not None:
                payload = _reshard_node(payload, entry.grid)
            res = _Resident(payload, int(rec["info"]),
                            _tree_nbytes(payload, per_chip=True),
                            _tree_nbytes(payload))
            session._cache[h] = res
            # an appended-QR resident's row count grew past its
            # registered operand's; the record carries the truth
            entry.m = int(rec["m"])
            session.metrics.inc("restored_residents_total")
            attr = session.attribution
            if attr is not None:
                if rec["heat"]:
                    attr.import_heat(h, rec["heat"],
                                     tenant=entry.tenant,
                                     last_access=rec["last_access"])
                inc = attr.touch_residency(entry.tenant, h, res.nbytes)
                if inc:
                    session.metrics.inc("residency_byte_seconds_total",
                                        inc)
            if session.numerics is not None and rec["health"]:
                session.numerics.import_state(h, rec["health"])
            session._evict_to_budget(keep=h)
        summary["restored"].append(h)
    return summary
