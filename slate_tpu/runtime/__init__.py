"""slate_tpu.runtime — resident-factorization solve service.

The serving layer over the simplified-API verbs: a Session keeps
factored operators hot in an HBM-budget LRU cache, a Batcher coalesces
same-shape solve requests into one stacked dispatch, an Executor gives
an async submit/future front end with AOT warmup and bounded retry, and
Metrics exports counters + latency percentiles as JSON and Prometheus
text. Observability (slate_tpu.obs): enable ``session.tracer`` for a
request-scoped span tree per served solve (batch → request /
solve → factor / dispatch / block) exportable as Chrome-trace JSON, and
``session.serve_obs()`` for the /metrics, /healthz, /trace.json HTTP
endpoint. See DESIGN.md ("Serving runtime", "Observability") and
bench_serve.py for the measured win.
"""

from .batching import Batcher
from .executor import Executor
from .metrics import Histogram, Metrics
from .session import Session, default_session

__all__ = ["Batcher", "Executor", "Histogram", "Metrics", "Session",
           "default_session"]
