"""slate_tpu.runtime — resident-factorization solve service.

The serving layer over the simplified-API verbs: a Session keeps
factored operators hot in an HBM-budget LRU cache, a Batcher coalesces
same-shape solve requests into one stacked dispatch (with per-request
deadlines, admission control, and cost-ordered load shedding —
ShedPolicy), an Executor gives an async submit/future front end with
AOT warmup, exponential-backoff retry, and a circuit breaker walking
the declared degradation ladder (faults.DEGRADATION_LADDER), and
Metrics exports counters + latency percentiles as JSON and Prometheus
text. ``faults`` makes every failure path deterministically
injectable (seeded FaultInjector; tools/chaos_serve.py soaks it). Observability (slate_tpu.obs): enable ``session.tracer`` for a
request-scoped span tree per served solve (batch → request /
solve → factor / dispatch / block) exportable as Chrome-trace JSON, and
``session.serve_obs()`` for the /metrics, /healthz, /trace.json HTTP
endpoint. See DESIGN.md ("Serving runtime", "Observability") and
bench_serve.py for the measured win.
"""

from .batching import Batcher, ShedPolicy
from .checkpoint import (CHECKPOINT_SCHEMA, CheckpointCorrupt,
                         load_manifest, restore_session, save_session,
                         validate_manifest)
from .executor import Executor
from .faults import (DEGRADATION_LADDER, DeadlineExceeded, FaultInjector,
                     FaultPlan, FaultSpec, QuotaExceeded, RequestShed,
                     TransientDispatchError, default_plan)
from .fleet import Fleet
from .metrics import Histogram, Metrics
from .session import Session, default_session
from .tenancy import (DeficitScheduler, TenantPolicy, TenantTable,
                      TokenBucket)

__all__ = ["Batcher", "Executor", "Fleet", "Histogram", "Metrics",
           "Session", "ShedPolicy", "default_session",
           "CHECKPOINT_SCHEMA", "CheckpointCorrupt", "load_manifest",
           "restore_session", "save_session", "validate_manifest",
           "DEGRADATION_LADDER", "DeadlineExceeded", "FaultInjector",
           "FaultPlan", "FaultSpec", "QuotaExceeded", "RequestShed",
           "TransientDispatchError", "default_plan",
           "DeficitScheduler", "TenantPolicy", "TenantTable",
           "TokenBucket"]
