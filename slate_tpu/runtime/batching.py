"""Shape-bucketing request batcher.

N callers each asking for one right-hand side against the same resident
operator should cost ONE kernel launch, not N: requests are bucketed by
(handle, single-RHS shape, dtype), column-stacked into one (n, K)
right-hand side, solved once through the Session, and split back —
every *_solve_using_factor verb is column-independent, and dense
right-hand sides are tile-padded to the operator's nb, so a K≤nb batch
runs the SAME padded shape (hence the same compiled executable) as a
single request and returns bit-identical per-request results.

**Distinct-operator grouping (round 10).** Small-problem operators
(``Session`` op kinds ``lu_small``/``chol_small``) are additionally
grouped ACROSS handles: every request whose operator shares
(op, n, dtype) and whose rhs shares a shape lands in one bucket
regardless of which operator it targets, and the bucket dispatches as
ONE batched program pass (``Session.solve_small_batched`` — batched
factor for the cache misses, one batched solve over the stacked
resident factors) instead of B per-request programs. Results are
bit-identical to per-request dispatch because the batched kernels'
arithmetic is batch-independent (linalg/batched); a singular item
fails ITS future with the per-item info and leaves its bucket
neighbors' solutions untouched.

A bucket dispatches when it reaches ``max_batch`` or when its oldest
request has waited ``max_wait`` seconds (the serving deadline knob:
latency floor vs launch amortization). The Batcher itself owns no
thread — the Executor drives ``pop_ready``/``run``; ``flush`` exists
for synchronous callers and tests.

**Tenant isolation (round 18).** With a
:class:`~.tenancy.TenantTable` attached (its own ``tenant_policies=``
or the Session's), ``submit`` enforces per-tenant quotas at the door
(in-flight cap, optional flops/s rate — a counted
:class:`~.faults.QuotaExceeded`, never a silent drop) and
``pop_ready`` replaces FIFO bucket order with deficit-weighted
round-robin over per-tenant ready buckets (same buckets, same
programs, different ORDER — bit-parity pinned; the starvation bound
is the :class:`~.tenancy.DeficitScheduler` docstring's hand-pinned
argument). Tenant-scoped SLO objectives shed the burning tenant's own
cheapest requests first (:meth:`maybe_shed`). ``None`` (the default)
is the pre-round-18 behavior: one is-None check per seam, zero
allocation.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Dict, Hashable, List, Optional, Tuple

import numpy as np

from ..core.exceptions import SlateError
from ..obs.attribution import s_grid as _s_grid
from ..obs.tracing import NOOP_SPAN as _NOOP_SPAN
from .faults import DeadlineExceeded, QuotaExceeded, RequestShed
from .session import Session
from .tenancy import DeficitScheduler, TokenBucket, as_table


@dataclasses.dataclass
class _Request:
    b: np.ndarray          # always 2-D (rows, 1..k) column block
    vector: bool           # original rank (reshape on completion)
    future: Future
    t_submit: float
    # the operator this request targets (small-problem grouped buckets
    # hold requests against DISTINCT handles; same-operator buckets
    # carry it in the key too)
    handle: Hashable = None
    # obs span, opened at dispatch (parent: the batch span) and closed
    # at future resolution; None while tracing is off or pre-dispatch
    span: object = None
    # absolute monotonic deadline (round 14): past it the request
    # FAILS FAST (DeadlineExceeded, counted, span-annotated) instead
    # of occupying a batch lane; None = no deadline
    deadline: Optional[float] = None
    # explicit per-request tenant override (round 15): None = the
    # operator's registered tenant (resolved lazily at the attribution
    # seams — the disabled path never resolves). An explicit tenant
    # joins the bucket key, so one dispatched bucket is one tenant and
    # the Session-side work attribution stays exact.
    tenant: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class ShedPolicy:
    """Admission-control + load-shedding knobs (round 14 reflexes).

    ``max_queue_depth`` is the ADMISSION bound: a submit that would
    push the queue past it is turned away at the door (its future
    fails immediately with :class:`RequestShed`; the enqueue never
    happens). The overload triggers govern SHEDDING of already-queued
    requests: ``max_age_s`` fires when ``oldest_request_age_s``
    (cancelled requests excluded) exceeds it, ``burn_threshold`` when
    the SLO tracker's worst short-window burn rate does (checked at
    most every ``check_interval_s`` — burn evaluation walks event
    windows and must not run per wakeup). A shed event drops
    ``shed_fraction`` of the queue, CHEAPEST-TO-RECOMPUTE FIRST
    (``Session.recompute_cost`` — the round-9 cost-log ordering:
    resident-factor solves are cheap to retry, cold factor+solve
    requests are not), never below ``min_queue_depth``.

    ``None`` fields disable their trigger; a Batcher with no policy
    pays one is-None check per seam (the round-8 discipline)."""

    max_queue_depth: Optional[int] = None
    max_age_s: Optional[float] = None
    burn_threshold: Optional[float] = None
    shed_fraction: float = 0.5
    min_queue_depth: int = 1
    check_interval_s: float = 0.05

    def __post_init__(self):
        if not (0.0 < self.shed_fraction <= 1.0):
            raise ValueError("ShedPolicy: shed_fraction must be in "
                             f"(0, 1], got {self.shed_fraction}")


BucketKey = Tuple[Hashable, Tuple[int, ...], str]

# first element of a grouped small-problem bucket key — a private
# sentinel, so no user handle (which may be any hashable, including
# the string "small") can collide with it
_SMALL = object()


class Batcher:
    """Coalesces same-operator/same-shape solve requests (see module
    docstring). Thread-safe; dispatch runs on the caller of ``run``."""

    def __init__(self, session: Session, max_batch: int = 32,
                 max_wait: float = 2e-3, pad_widths: bool = False,
                 shed_policy: Optional[ShedPolicy] = None,
                 tenant_policies=None, clock=time.monotonic):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.session = session
        self.max_batch = max_batch
        self.max_wait = max_wait
        # admission control + load shedding (round 14): None = off,
        # one is-None check per submit / worker wakeup
        self.shed_policy = shed_policy
        self._last_burn_check = 0.0
        # tenant isolation (round 18, runtime/tenancy.py): quotas at
        # the submit seam (in-flight cap / flops-rate -> counted
        # QuotaExceeded, never a silent drop) and deficit-weighted
        # round-robin dispatch order in pop_ready. Defaults to the
        # SESSION's table so one declaration covers both seams; None =
        # the pre-round-18 FIFO behavior, one is-None check per seam,
        # zero allocation (the round-8 discipline, pinned by test)
        self.tenants = (as_table(tenant_policies)
                        if tenant_policies is not None
                        else getattr(session, "tenant_policies", None))
        self._clock = clock
        if self.tenants is not None:
            self._sched = DeficitScheduler(self.tenants)
            self._deficit_gauges: set = set()
            self._tenant_inflight: Dict[str, int] = {}
            # LRU-capped (tenant strings are client input — arbitrary
            # cardinality must not leak memory; a pruned tenant's
            # bucket restarts full, which is the permissive-but-
            # bounded direction)
            from collections import OrderedDict as _OD
            self._tenant_tokens: "_OD[str, TokenBucket]" = _OD()
            self._tenant_tokens_cap = 1024
        else:
            self._sched = None
        # pow2 width quantization (round 11): pad the stacked
        # right-hand side out to the next power of two with zero
        # columns before dispatch, so a varying coalesced width lowers
        # to O(log max_batch) distinct solve programs instead of one
        # per width — the knob that keeps a MESH session's expensive
        # sharded AOT compiles bounded. Per-request results are
        # untouched: every *_solve_using_factor verb is
        # column-independent, so the extra zero columns never feed the
        # real ones (and they are sliced off before futures resolve).
        self.pad_widths = pad_widths
        self._lock = threading.Lock()
        self._buckets: Dict[BucketKey, List[_Request]] = {}
        # incrementally-maintained backpressure state (round 12): the
        # submit hot path publishes gauges from these two counters
        # instead of scanning every bucket while holding the lock;
        # pop_ready recomputes them exactly from the queue
        self._depth = 0
        self._max_backlog = 0
        self._oldest: Optional[float] = None  # head submit time

    # -- submission --------------------------------------------------------

    def submit(self, handle: Hashable, b, timeout_s: Optional[float]
               = None, tenant: Optional[str] = None) -> Future:
        """Enqueue one solve request; resolves to the solution array
        with the same rank as ``b``. Small-problem operators are
        grouped across handles (module docstring): their bucket key is
        (op, n, dtype, rhs-shape), not the handle.

        ``timeout_s`` (round 14): a per-request deadline carried from
        here through bucket formation to dispatch — once it passes the
        future fails fast with :class:`~.faults.DeadlineExceeded`
        (counted in ``deadline_expired_total``) instead of occupying a
        batch lane. With a :class:`ShedPolicy` admission bound, a
        submit against a full queue returns an ALREADY-FAILED future
        (:class:`~.faults.RequestShed`; ``admission_rejected_total``)
        without enqueueing.

        ``tenant`` (round 15): per-request attribution override. An
        EXPLICIT tenant joins the bucket key (requests with different
        explicit tenants never coalesce — one dispatched program is
        one tenant's work, which keeps the attribution exact and is
        the grain the item-1 weighted-fair scheduler will schedule
        at); ``None`` — every existing caller — keeps today's keys
        byte-identical and attributes to the operator's registered
        tenant."""
        req, rejection = self.submit_deferred(handle, b,
                                              timeout_s=timeout_s,
                                              tenant=tenant)
        if rejection is not None:
            self.reject_admission(req, rejection)
        return req.future

    def submit_deferred(self, handle: Hashable, b,
                        timeout_s: Optional[float] = None,
                        tenant: Optional[str] = None
                        ) -> Tuple[_Request, Optional[Exception]]:
        """The enqueue half of :meth:`submit`: returns ``(request,
        rejection)`` WITHOUT resolving an admission-rejected future —
        for callers that hold their own lock across the enqueue (the
        Executor's shutdown-atomic submit) and must run
        :meth:`reject_admission` after releasing it: resolving a
        future runs client done-callbacks, and a callback that
        re-enters the Executor would deadlock on its non-reentrant
        lock."""
        b = np.asarray(b)
        vector = b.ndim == 1
        b2 = b[:, None] if vector else b
        skey = self.session.small_group_key(handle)
        # an explicit tenant splits the bucket (one program = one
        # tenant); spliced BEFORE the (shape, dtype) tail so grouped
        # dispatch keeps reading op=key[1], n=key[2], shape=key[-2],
        # dtype=key[-1] — and None (every existing caller) keeps the
        # key tuples byte-identical to round 14
        tsplit = () if tenant is None else (str(tenant),)
        if skey is not None:
            if not tsplit and self.tenants is not None:
                # round 18: with a tenant table attached, implicit-
                # tenant SMALL groups split by the OPERATOR tenant too
                # — otherwise two tenants' same-(op, n, dtype)
                # operators would coalesce into one bucket and the
                # aggressor's backlog would ride the victim's weight
                # through the DRR scheduler (review finding, pinned).
                # Per-handle dense buckets are single-operator-tenant
                # by construction; without a table the keys stay
                # byte-identical to round 14 (the round-15 pin).
                tsplit = (self.session.request_tenant(handle, None),)
            key: BucketKey = (_SMALL,) + skey + tsplit + (
                tuple(b2.shape), str(b2.dtype))
        else:
            key = (handle,) + tsplit + (tuple(b2.shape), str(b2.dtype))
        req = _Request(b2, vector, Future(), time.monotonic(),
                       handle=handle,
                       tenant=None if tenant is None else str(tenant))
        if timeout_s is not None:
            req.deadline = req.t_submit + timeout_s
        self.session.metrics.inc("requests_total")
        pol = self.shed_policy
        table = self.tenants
        rt = tpol = None
        with self._lock:
            if table is not None:
                # tenant quota gate (round 18): the tenant's OWN
                # limits, checked before the global admission bound —
                # a QuotaExceeded is counted (quota_rejections_total +
                # the quota_rejected outcome) by reject_admission,
                # never a silent drop
                rt = self.session.request_tenant(handle, req.tenant)
                tpol = table.policy(rt)
                if tpol is not None:
                    if (tpol.max_in_flight is not None
                            and self._tenant_inflight.get(rt, 0)
                            >= tpol.max_in_flight):
                        return req, QuotaExceeded(
                            f"tenant {rt!r} is over its in-flight cap "
                            f"({tpol.max_in_flight}); retry with "
                            "backoff — other tenants are unaffected")
            if (pol is not None and pol.max_queue_depth is not None
                    and self._depth >= pol.max_queue_depth):
                return req, RequestShed(
                    f"admission control: queue depth >= "
                    f"{pol.max_queue_depth}; request rejected at the "
                    "door (retry with backoff)")
            if table is not None and tpol is not None \
                    and tpol.flops_per_s is not None:
                # the rate DEBIT runs last — after every reject-only
                # check — so a request turned away at the admission
                # bound never consumes the tenant's rate budget
                tb = self._tenant_tokens.get(rt)
                if tb is None:
                    tb = self._tenant_tokens[rt] = TokenBucket(
                        tpol.flops_per_s,
                        tpol.flops_per_s * tpol.burst_s,
                        clock=self._clock)
                    while len(self._tenant_tokens) > \
                            self._tenant_tokens_cap:
                        self._tenant_tokens.popitem(last=False)
                else:
                    self._tenant_tokens.move_to_end(rt)
                cost = self.session.recompute_cost(handle, b2.shape[1])
                if not tb.admit(cost):
                    return req, QuotaExceeded(
                        f"tenant {rt!r} is over its "
                        f"{tpol.flops_per_s:.3g} model-flops/s rate; "
                        "retry with backoff — other tenants are "
                        "unaffected")
            bucket = self._buckets.setdefault(key, [])
            bucket.append(req)
            # cheap incremental gauge publish (one batched metrics-
            # lock hold, no full-queue scan on the enqueue hot
            # path); oldest_request_age_s is as of the last queue
            # transition — pop_ready and backpressure() recompute
            # it exactly
            self._depth += 1
            self._max_backlog = max(self._max_backlog, len(bucket))
            if self._oldest is None:
                self._oldest = req.t_submit  # only pops move it back
            gauges = {
                "queue_depth": self._depth,
                "queued_buckets": len(self._buckets),
                "max_bucket_backlog": self._max_backlog,
                "oldest_request_age_s": req.t_submit - self._oldest,
            }
            if rt is not None:
                # in-flight = submitted and unresolved: the cap's
                # denominator. The done-callback decrements on ANY
                # resolution path (completed/failed/shed/expired/
                # cancelled) — registered while the future is pending,
                # so no client code runs under this lock
                n_inf = self._tenant_inflight.get(rt, 0) + 1
                self._tenant_inflight[rt] = n_inf
                req.future.add_done_callback(
                    lambda f, t=rt: self._dec_inflight(t))
                gauges[f"tenant_quota_inflight:{rt}"] = n_inf
            self.session.metrics.set_gauges(gauges)
        return req, None

    def _dec_inflight(self, tenant: str):
        """Future-resolution callback: one tenant's in-flight count
        down (any resolution path — the cap meters live requests). A
        drained tenant's entry AND gauge are dropped — tenant-string
        churn must not grow state or scrape cardinality without bound
        (the round-15 drop_gauge discipline)."""
        with self._lock:
            n = self._tenant_inflight.get(tenant, 0) - 1
            if n <= 0:
                self._tenant_inflight.pop(tenant, None)
            else:
                self._tenant_inflight[tenant] = n
        if n <= 0:
            self.session.metrics.drop_gauge(
                f"tenant_quota_inflight:{tenant}")
        else:
            self.session.metrics.set_gauge(
                f"tenant_quota_inflight:{tenant}", n)

    def tenant_inflight(self, tenant: str) -> int:
        with self._lock:
            return (0 if self._sched is None
                    else self._tenant_inflight.get(str(tenant), 0))

    def reject_admission(self, req: _Request, rejection: Exception):
        """Resolve an admission- or quota-rejected request (call with
        NO locks held — set_exception may run client callbacks). A
        :class:`~.faults.QuotaExceeded` counts the round-18 partition
        (``quota_rejections_total`` + the tenant-labeled
        ``quota_rejected`` outcome); everything else is the round-14
        admission bound."""
        rec = self.session.recorder
        if isinstance(rejection, QuotaExceeded):
            self.session.metrics.inc("quota_rejections_total")
            attr = self.session.attribution
            if attr is not None:
                attr.record_outcome(self._rtenant(req), req.handle,
                                    "quota_rejected")
            if rec is not None:
                rec.decision("quota_reject", handle=req.handle,
                             tenant=self._rtenant(req),
                             outcome="rejected",
                             inputs={"error": str(rejection)})
        else:
            self.session.metrics.inc("admission_rejected_total")
            if rec is not None:
                rec.decision("admission_reject", handle=req.handle,
                             tenant=req.tenant, outcome="rejected",
                             inputs={"error": str(rejection)})
        req.future.set_exception(rejection)

    def pending(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._buckets.values())

    # -- backpressure telemetry (round 12) ---------------------------------

    @staticmethod
    def _head_submit(reqs) -> Optional[float]:
        """Submit time of the oldest LIVE request in a bucket: a
        cancelled-but-undetached request must not pin
        ``oldest_request_age_s`` high (it costs nothing to leave
        queued and nothing to skip at dispatch) — before this, one
        abandoned future could hold the age gauge at its own age
        forever and trigger spurious load shedding."""
        for r in reqs:
            if not r.future.cancelled():
                return r.t_submit
        return None

    def _update_backpressure_locked(self, now: Optional[float] = None):
        """Caller holds the lock. Publish the queue's truth as gauges —
        before this, the only queue signal was the indirect ``queue_s``
        span attribute. Exact recompute, run on pops (the submit hot
        path publishes from the incremental counters instead — module
        state above), so a scrape between dispatches reads the state
        as of the last queue transition. Also resyncs the incremental
        counters."""
        now = time.monotonic() if now is None else now
        m = self.session.metrics
        depths = [len(v) for v in self._buckets.values() if v]
        self._depth = sum(depths)
        self._max_backlog = max(depths, default=0)
        heads = [self._head_submit(reqs)
                 for reqs in self._buckets.values() if reqs]
        self._oldest = min((h for h in heads if h is not None),
                           default=None)
        m.set_gauges({
            "queue_depth": self._depth,
            "queued_buckets": len(depths),
            "max_bucket_backlog": self._max_backlog,
            "oldest_request_age_s": (0.0 if self._oldest is None
                                     else now - self._oldest),
        })

    def backpressure(self) -> dict:
        """Point-in-time queue state, per bucket (JSON-friendly: the
        /metrics gauges carry the aggregates; this is the labeled
        breakdown a debugger wants)."""
        now = time.monotonic()
        with self._lock:
            per_bucket = {}
            for key, reqs in self._buckets.items():
                if not reqs:
                    continue
                head = self._head_submit(reqs)  # cancelled excluded
                per_bucket[repr(key)] = {
                    "backlog": len(reqs),
                    "oldest_age_s": (0.0 if head is None
                                     else now - head)}
        return {
            "queue_depth": sum(v["backlog"] for v in per_bucket.values()),
            "queued_buckets": len(per_bucket),
            "oldest_request_age_s": max(
                (v["oldest_age_s"] for v in per_bucket.values()),
                default=0.0),
            "per_bucket": per_bucket,
        }

    # -- readiness ---------------------------------------------------------

    def next_deadline(self) -> Optional[float]:
        """Earliest monotonic time the worker must act: a bucket's
        max-wait dispatch deadline or a request's own deadline,
        whichever is sooner — so an expiring request fails fast at its
        deadline instead of at the next bucket flush (and an IDLE
        worker sleeps untimed instead of polling)."""
        with self._lock:
            vals = []
            for reqs in self._buckets.values():
                if not reqs:
                    continue
                vals.append(reqs[0].t_submit + self.max_wait)
                vals.extend(r.deadline for r in reqs
                            if r.deadline is not None)
        return min(vals) if vals else None

    def pop_ready(self, now: Optional[float] = None, force: bool = False,
                  expired_out: Optional[List[_Request]] = None
                  ) -> List[Tuple[BucketKey, List[_Request]]]:
        """Detach buckets that are full or past deadline (all of them
        when ``force``). Requests beyond max_batch stay queued.
        Requests past their OWN deadline leave the queue here and fail
        fast (counted, span-annotated) — they never occupy a batch
        lane, and a bucket holding only expired/cancelled requests
        drains without dispatching. ``expired_out``: collect the
        expired requests instead of failing them here — for callers
        that hold a lock of their own (the Executor worker) and must
        run :meth:`_fail_expired` after releasing it (resolving a
        future runs client callbacks)."""
        now = time.monotonic() if now is None else now
        out: List[Tuple[BucketKey, List[_Request]]] = []
        expired: List[_Request] = []
        with self._lock:
            for key in list(self._buckets):
                reqs = self._buckets[key]
                if any(r.deadline is not None and r.deadline <= now
                       for r in reqs):
                    live = []
                    for r in reqs:
                        if (r.deadline is not None and r.deadline <= now
                                and not r.future.done()):
                            expired.append(r)
                        else:
                            live.append(r)
                    self._buckets[key] = reqs = live
                while (len(reqs) >= self.max_batch
                       or (reqs and force)
                       or (reqs and now - reqs[0].t_submit >= self.max_wait)):
                    take, rest = reqs[:self.max_batch], reqs[self.max_batch:]
                    out.append((key, take))
                    self._buckets[key] = reqs = rest
                if not reqs:
                    del self._buckets[key]
            if self._sched is not None and len(out) > 1:
                # round 18: deficit-weighted round-robin dispatch
                # order over per-tenant ready buckets instead of FIFO
                # dict order — same buckets, same programs, different
                # ORDER (bit-parity pinned), so a noisy tenant's
                # backlog cannot push every other tenant's bucket to
                # the back of the dispatch line. The starvation bound
                # is the DeficitScheduler docstring's hand-pinned
                # argument. Bucket tenant: the explicit tenant rides
                # the key (one bucket = one tenant, the round-15
                # invariant), else the first request's operator tenant
                # (request_tenant is lock-free).
                out = self._sched.order([
                    (self.session.request_tenant(reqs[0].handle,
                                                 reqs[0].tenant),
                     len(reqs), (key, reqs))
                    for key, reqs in out])
                deficits = self._sched.deficits()
                self.session.metrics.set_gauges({
                    f"fair_share_deficit:{t}": d
                    for t, d in deficits.items()})
                # gauges for tenants the scheduler pruned are dropped
                # (tenant churn must not grow scrape cardinality)
                for t in self._deficit_gauges - set(deficits):
                    self.session.metrics.drop_gauge(
                        f"fair_share_deficit:{t}")
                self._deficit_gauges = set(deficits)
            if out or expired:
                self._update_backpressure_locked(now)
        if expired_out is None:
            self._fail_expired(expired, now)
        else:
            expired_out.extend(expired)
        return out

    def _fail_expired(self, reqs: List[_Request], now: float):
        """Fail deadline-expired requests fast (outside the queue
        lock: resolving a future can run client callbacks). Counted
        (``deadline_expired_total``), span-annotated, and recorded to
        the SLO error stream — an expiry is a client-visible failure."""
        if not reqs:
            return
        m = self.session.metrics
        tr = self.session.tracer
        slo = self.session.slo
        attr = self.session.attribution
        for r in reqs:
            err = DeadlineExceeded(
                f"deadline exceeded after {now - r.t_submit:.4f}s in "
                "queue (failed fast without occupying a batch lane)")
            try:
                r.future.set_exception(err)
            except InvalidStateError:
                continue  # client cancelled first; counted elsewhere
            m.inc("deadline_expired_total")
            if attr is not None:
                attr.record_outcome(self._rtenant(r), r.handle,
                                    "expired")
            rec = self.session.recorder
            if rec is not None:
                rec.decision("deadline_expired", handle=r.handle,
                             tenant=r.tenant, outcome="failed_fast",
                             inputs={"queue_s": now - r.t_submit,
                                     "deadline_s": r.deadline})
            if tr.enabled:
                sp = r.span or tr.start_span(
                    "serve.request", kind="request",
                    handle=repr(r.handle), queue_s=now - r.t_submit)
                tr.finish_span(sp, error=err, deadline_expired=True)
                r.span = None
            if slo is not None:
                meta = self.session.op_meta(r.handle)
                if meta is not None:
                    slo.record_request(meta[0], meta[1],
                                       now - r.t_submit, ok=False,
                                       tenant=self._rtenant(r))

    # -- admission control + load shedding (round 14) ----------------------

    def maybe_shed(self, now: Optional[float] = None) -> int:
        """The load-shedding reflex, driven by the Executor worker each
        wakeup (one is-None check when no policy). When an overload
        trigger fires — ``oldest_request_age_s`` past ``max_age_s``,
        or the SLO tracker's worst short-window burn rate past
        ``burn_threshold`` — drop ``shed_fraction`` of the queue,
        CHEAPEST-TO-RECOMPUTE FIRST (``Session.recompute_cost``: a
        request against a resident factor re-costs one solve; a cold
        one re-costs factor + solve), failing the shed futures with
        :class:`~.faults.RequestShed`. Returns the number shed."""
        pol = self.shed_policy
        if pol is None:
            return 0
        now = time.monotonic() if now is None else now
        with self._lock:
            depth, oldest = self._depth, self._oldest
        if depth < max(pol.min_queue_depth, 1):
            self.session.metrics.set_gauge("shedding_active", 0.0)
            return 0
        trigger = None
        global_trigger = None
        shed_tenant: Optional[str] = None
        if (pol.max_age_s is not None and oldest is not None
                and now - oldest > pol.max_age_s):
            trigger = f"oldest_request_age_s > {pol.max_age_s}"
        if trigger is None and pol.burn_threshold is not None:
            slo = self.session.slo
            if (slo is not None
                    and now - self._last_burn_check
                    >= pol.check_interval_s):
                self._last_burn_check = now
                # round 18: tenant-scoped objectives shed FIRST and
                # shed ONLY the burning tenant's requests — a noisy
                # tenant pays for its own overload before any global
                # trigger touches its victims' traffic. The GLOBAL
                # burn check still runs (worst_burn_rate walks every
                # objective, tenant-scoped included) so that a burning
                # tenant with nothing left queued cannot suppress the
                # round-14 overload reflex for everyone else.
                if self.tenants is not None:
                    rates = slo.tenant_burn_rates(now=now)
                    over = {t: b for t, b in rates.items()
                            if b > pol.burn_threshold}
                    if over:
                        shed_tenant = max(over, key=lambda t: over[t])
                        trigger = (f"tenant {shed_tenant!r} slo burn "
                                   f"rate {over[shed_tenant]:.3g} > "
                                   f"{pol.burn_threshold}")
                burn = slo.worst_burn_rate(now=now)
                if burn > pol.burn_threshold:
                    global_trigger = (f"slo burn rate {burn:.3g} > "
                                      f"{pol.burn_threshold}")
                    if trigger is None:
                        trigger = global_trigger
                        shed_tenant = None
        if trigger is None:
            self.session.metrics.set_gauge("shedding_active", 0.0)
            return 0
        victims: List[_Request] = []
        with self._lock:
            queued = [(key, r) for key, reqs in self._buckets.items()
                      for r in reqs if not r.future.done()]
            pool = (queued if shed_tenant is None else
                    [kr for kr in queued
                     if self._rtenant(kr[1]) == shed_tenant])
            if not pool and shed_tenant is not None \
                    and global_trigger is not None:
                # the burning tenant has nothing queued: fall back to
                # the global overload reflex instead of skipping the
                # whole interval (review finding, pinned)
                trigger, shed_tenant = global_trigger, None
                pool = queued
            # the floor: never shed below min_queue_depth live
            # requests (the docstring contract); a tenant-scoped shed
            # draws only from that tenant's pool
            n_shed = min(max(1, int(len(pool) * pol.shed_fraction)),
                         len(queued) - max(pol.min_queue_depth, 1),
                         len(pool))
            if n_shed <= 0:
                self.session.metrics.set_gauge("shedding_active", 0.0)
                return 0
            # cheapest-to-recompute first; newest first among equals
            # (the oldest requests are closest to being served)
            pool.sort(key=lambda kr: (
                self.session.recompute_cost(kr[1].handle,
                                            kr[1].b.shape[1]),
                -kr[1].t_submit))
            chosen = pool[:n_shed]
            drop = {id(r) for _, r in chosen}
            for key in list(self._buckets):
                kept = [r for r in self._buckets[key]
                        if id(r) not in drop]
                if kept:
                    self._buckets[key] = kept
                else:
                    del self._buckets[key]
            victims = [r for _, r in chosen]
            self._update_backpressure_locked(now)
        m = self.session.metrics
        m.inc("load_sheds_total")
        if shed_tenant is not None:
            m.inc("tenant_sheds_total")
        m.set_gauge("shedding_active", 1.0)
        tr = self.session.tracer
        attr = self.session.attribution
        shed = 0
        for r in victims:
            try:
                r.future.set_exception(RequestShed(
                    f"load shed ({trigger}); cheapest-to-recompute "
                    "first per the session cost log — retry with "
                    "backoff"))
            except InvalidStateError:
                continue  # cancelled concurrently
            shed += 1
            if attr is not None:
                attr.record_outcome(self._rtenant(r), r.handle, "shed")
            if tr.enabled:
                sp = r.span or tr.start_span(
                    "serve.request", kind="request",
                    handle=repr(r.handle), queue_s=now - r.t_submit)
                tr.finish_span(sp, shed=True)
                r.span = None
        m.inc("shed_requests_total", shed)
        rec = self.session.recorder
        if rec is not None and shed:
            # ONE wave = ONE decision; count carries the victim total
            # (journal parity vs shed_requests_total sums count)
            rec.decision("shed", tenant=shed_tenant, outcome=trigger,
                         count=shed,
                         inputs={"trigger": trigger,
                                 "queued": len(queued),
                                 "victims": shed})
        return shed

    # -- dispatch ----------------------------------------------------------

    def _rtenant(self, r: _Request) -> str:
        """Resolved tenant of one request (explicit override ->
        operator tenant -> default). Only called from seams that
        already verified the attribution/SLO consumer exists."""
        return self.session.request_tenant(r.handle, r.tenant)

    def _attr_queue_wait(self, attr, r: _Request, now: float):
        """Caller verified ``attr is not None``: queue-wait seconds on
        the dyadic grid, same snapped value to the per-tenant cell and
        the ``queue_seconds_total`` global (the conservation seam)."""
        qs = _s_grid(now - r.t_submit)
        if qs:
            self.session.metrics.inc("queue_seconds_total", qs)
            attr.record("queue_seconds", self._rtenant(r), r.handle, qs)

    def run(self, key: BucketKey, reqs: List[_Request]):
        """Solve one detached bucket: stack → one Session solve → split.
        Future resolution (including request latency metrics) happens
        here; exceptions propagate to the caller AND the unresolved
        futures are left pending so the caller can retry (see Executor).
        Idempotent over futures: already-done (resolved on an earlier
        attempt, or client-cancelled) requests are skipped, so a retry
        only covers what is still unresolved.

        Tracing: the batch span is the trace ROOT — N requests meet in
        one dispatch, and a tree has one root, so the per-request spans
        are parented onto the batch span (their queue wait rides along
        as the ``queue_s`` attribute, their end is future resolution);
        the Session's solve/factor/dispatch spans nest under the batch
        span via the contextvar scope."""
        if key and key[0] is _SMALL:
            return self._run_small(key, reqs)
        # key = (handle[, tenant], shape, dtype): the optional round-15
        # tenant splice sits between the handle and the fixed tail
        handle = key[0]
        kshape, kdtype = key[-2], key[-1]
        now = time.monotonic()
        live = self._live(reqs, now)
        if not live:
            return
        tr = self.session.tracer
        bctx = (tr.span("serve.batch", handle=repr(handle),
                        batch_size=len(live), shape=list(kshape),
                        dtype=kdtype) if tr.enabled else _NOOP_SPAN)
        m = self.session.metrics
        attr = self.session.attribution
        with bctx as bspan:
            # exemplar join key: the batch's trace id (NOOP -> None)
            tid = getattr(bspan, "trace_id", None)
            for r in live:
                # None unless this attempt re-runs a bucket whose spans
                # the Executor already closed (errored attempt) — each
                # attempt gets spans nested in ITS batch span
                if r.span is None:
                    r.span = tr.start_span(
                        "serve.request", parent=bspan, kind="request",
                        handle=repr(handle), shape=list(r.b.shape),
                        dtype=kdtype, queue_s=now - r.t_submit)
                # lifecycle stage 1 (round 12): submit -> dispatch start
                m.observe("stage_queue_wait", now - r.t_submit,
                          exemplar=tid)
                if attr is not None:
                    self._attr_queue_wait(attr, r, now)
            try:
                t_form = time.monotonic()
                stacked = np.concatenate([r.b for r in live], axis=1)
                cols = stacked.shape[1]
                if self.pad_widths:
                    # the shared pow2 quantum (also the batch-dim
                    # bucket of linalg/batched) — one definition, so
                    # the Batcher's padded widths can never drift
                    # from the bucketing the rest of the repo primes
                    from ..ops.blocked import bucket_pow2
                    # round 21: the width quantum comes through the
                    # tuning table when one is active for this handle's
                    # (op, n, dtype) — tuned_width_quantum is a single
                    # `tuning is None` check returning 1 when disabled,
                    # so the untuned pad grid is bit-identical to HEAD
                    w = bucket_pow2(
                        cols, self.session.tuned_width_quantum(handle))
                    if w > cols:
                        stacked = np.concatenate(
                            [stacked, np.zeros((stacked.shape[0],
                                                w - cols),
                                               stacked.dtype)], axis=1)
                # lifecycle stage 2: stack + width-pad the bucket (one
                # observation per batch — formation is batch-scoped)
                m.observe("stage_batch_form", time.monotonic() - t_form,
                          exemplar=tid)
                # served_cols: only the CLIENT columns count as solves
                # — the padded zero columns are executed work (the
                # ledgers see them, split out as padding_waste_flops/
                # bytes — round 12) but not served requests. Passed
                # only when padding actually happened — and the
                # round-15 tenant only when a request carried an
                # explicit override (the key split guarantees the
                # bucket is single-tenant) — so the common path keeps
                # the bare solve(handle, b) signature.
                kw = {}
                if stacked.shape[1] != cols:
                    kw["served_cols"] = cols
                if live[0].tenant is not None:
                    kw["tenant"] = live[0].tenant
                x = self.session.solve(handle, stacked, **kw)
            except Exception as e:
                # close this attempt's request spans INSIDE the batch
                # scope: the exception is about to close the batch span
                # via bctx.__exit__, and children ending after their
                # parent fail the Chrome-trace nesting validator
                for r in live:
                    tr.finish_span(r.span, error=e)
                raise
            m.inc("batches_total")
            m.observe("batch_size", float(len(live)))
            done = time.monotonic()
            slo = self.session.slo
            meta = (self.session.op_meta(handle)
                    if slo is not None else None)
            col = 0
            for r in live:
                w = r.b.shape[1]
                xi = x[:, col:col + w]
                col += w
                try:
                    r.future.set_result(xi[:, 0] if r.vector else xi)
                except InvalidStateError:
                    # client cancelled between our done() check and here
                    m.inc("cancelled_requests")
                    tr.finish_span(r.span, cancelled=True)
                    continue
                lat = done - r.t_submit
                m.inc("completed_requests")
                if attr is not None:
                    attr.record_outcome(self._rtenant(r), r.handle,
                                        "completed")
                m.observe("request_latency", lat, exemplar=tid)
                if meta is not None:
                    slo.record_request(meta[0], meta[1], lat, ok=True,
                                       tenant=self._rtenant(r))
                # total_s (submit -> resolve) is what the slow-request
                # log thresholds on — the client-visible latency
                tr.finish_span(r.span, total_s=lat)
            # lifecycle stage 5: solve done -> futures resolved (the
            # split/copy/notify reply cost, once per batch)
            m.observe("stage_reply", time.monotonic() - done,
                      exemplar=tid)

    def _live(self, reqs: List[_Request], now: float) -> List[_Request]:
        """Dispatch-start filter: drop already-resolved requests and
        fail the deadline-expired ones fast (a request can expire
        between detach and dispatch — e.g. while an earlier bucket
        retried through backoff)."""
        live, expired = [], []
        for r in reqs:
            if r.future.done():
                continue
            if r.deadline is not None and r.deadline <= now:
                expired.append(r)
            else:
                live.append(r)
        self._fail_expired(expired, now)
        return live

    def _run_small(self, key: BucketKey, reqs: List[_Request]):
        """Grouped small-problem dispatch: one bucket of DISTINCT-
        operator requests → ONE batched program pass through
        ``Session.solve_small_batched`` (batched factor for misses +
        one batched solve over the stacked factors). A singular item
        fails ITS OWN future with the per-item info (the SlateError the
        per-request path would have raised); its neighbors' solutions
        are bit-identical to what per-request dispatch produces."""
        # key = (_SMALL, op, n, op-dtype[, refine-policy], rhs-shape,
        # rhs-dtype): mixed entries (round 13) carry their RefinePolicy
        # in the group key so two policies never coalesce — read the
        # fixed head and tail, tolerate the optional middle
        op, n = key[1], key[2]
        shape, bdt = key[-2], key[-1]
        now = time.monotonic()
        live = self._live(reqs, now)
        if not live:
            return
        tr = self.session.tracer
        bctx = (tr.span("serve.batch", op=op, n=n, grouped=True,
                        batch_size=len(live), shape=list(shape),
                        dtype=bdt) if tr.enabled else _NOOP_SPAN)
        m = self.session.metrics
        attr = self.session.attribution
        with bctx as bspan:
            tid = getattr(bspan, "trace_id", None)
            for r in live:
                if r.span is None:
                    r.span = tr.start_span(
                        "serve.request", parent=bspan, kind="request",
                        handle=repr(r.handle), shape=list(r.b.shape),
                        dtype=bdt, queue_s=now - r.t_submit)
                m.observe("stage_queue_wait", now - r.t_submit,
                          exemplar=tid)
                if attr is not None:
                    self._attr_queue_wait(attr, r, now)
            try:
                # explicit tenant overrides ride the bucket key (one
                # bucket = one explicit tenant), so the per-item
                # tenants list is uniform; None lets the Session
                # resolve each item's operator tenant
                tenants = ([r.tenant for r in live]
                           if live[0].tenant is not None else None)
                xs, infos = self.session.solve_small_batched(
                    [r.handle for r in live], [r.b for r in live],
                    tenants=tenants)
            except Exception as e:
                for r in live:
                    tr.finish_span(r.span, error=e)
                raise
            m.inc("batches_total")
            m.observe("batch_size", float(len(live)))
            done = time.monotonic()
            slo = self.session.slo
            for i, r in enumerate(live):
                if infos[i] != 0:
                    err = SlateError(
                        f"Session: operator {r.handle!r} factorization "
                        f"failed (info={infos[i]})")
                    try:
                        r.future.set_exception(err)
                        m.inc("failed_requests_total")
                        if attr is not None:
                            attr.record_outcome(self._rtenant(r),
                                                r.handle, "failed")
                    except InvalidStateError:
                        m.inc("cancelled_requests")
                    if slo is not None:
                        slo.record_request(op, n, done - r.t_submit,
                                           ok=False,
                                           tenant=self._rtenant(r))
                    tr.finish_span(r.span, error=err)
                    continue
                xi = xs[i]
                try:
                    r.future.set_result(xi[:, 0] if r.vector else xi)
                except InvalidStateError:
                    m.inc("cancelled_requests")
                    tr.finish_span(r.span, cancelled=True)
                    continue
                lat = done - r.t_submit
                m.inc("completed_requests")
                if attr is not None:
                    attr.record_outcome(self._rtenant(r), r.handle,
                                        "completed")
                m.observe("request_latency", lat, exemplar=tid)
                if slo is not None:
                    slo.record_request(op, n, lat, ok=True,
                                       tenant=self._rtenant(r))
                tr.finish_span(r.span, total_s=lat)
            m.observe("stage_reply", time.monotonic() - done,
                      exemplar=tid)

    def run_degraded(self, key: BucketKey, reqs: List[_Request]):
        """The per-request rung of the degradation ladder
        (grouped→per_request, dense→per_request — faults.
        DEGRADATION_LADDER), walked by the Executor when a bucket's
        circuit breaker is open: every live request runs as its OWN
        Session.solve, so one poisoned lane (or a failure mode the
        coalesced program tickles) cannot fail its neighbors.
        Per-item isolation: a request whose own solve raises fails its
        own future; the rest are served. Futures resolve exactly once
        (already-done requests skipped, the run() discipline)."""
        m = self.session.metrics
        tr = self.session.tracer
        slo = self.session.slo
        attr = self.session.attribution
        now = time.monotonic()
        live = self._live(reqs, now)
        if not live:
            return
        m.inc("degraded_dispatches_total")
        bctx = (tr.span("serve.batch.degraded", batch_size=len(live),
                        ladder="per_request")
                if tr.enabled else _NOOP_SPAN)
        with bctx as bspan:
            tid = getattr(bspan, "trace_id", None)
            for r in live:
                if r.span is None:
                    r.span = tr.start_span(
                        "serve.request", parent=bspan, kind="request",
                        handle=repr(r.handle), degraded=True,
                        queue_s=now - r.t_submit)
                if attr is not None:
                    self._attr_queue_wait(attr, r, now)
                meta = self.session.op_meta(r.handle)
                try:
                    if r.tenant is not None:
                        x = self.session.solve(r.handle, r.b,
                                               tenant=r.tenant)
                    else:
                        x = self.session.solve(r.handle, r.b)
                except Exception as e:  # noqa: BLE001 — per-item isolation
                    try:
                        r.future.set_exception(e)
                        m.inc("failed_requests_total")
                        if attr is not None:
                            attr.record_outcome(self._rtenant(r),
                                                r.handle, "failed")
                    except InvalidStateError:
                        m.inc("cancelled_requests")
                    if slo is not None and meta is not None:
                        slo.record_request(
                            meta[0], meta[1],
                            time.monotonic() - r.t_submit, ok=False,
                            tenant=self._rtenant(r))
                    tr.finish_span(r.span, error=e)
                    continue
                done = time.monotonic()
                try:
                    r.future.set_result(x[:, 0] if r.vector else x)
                except InvalidStateError:
                    m.inc("cancelled_requests")
                    tr.finish_span(r.span, cancelled=True)
                    continue
                lat = done - r.t_submit
                m.inc("completed_requests")
                if attr is not None:
                    attr.record_outcome(self._rtenant(r), r.handle,
                                        "completed")
                m.observe("request_latency", lat, exemplar=tid)
                if slo is not None and meta is not None:
                    slo.record_request(meta[0], meta[1], lat, ok=True,
                                       tenant=self._rtenant(r))
                tr.finish_span(r.span, total_s=lat)

    def flush(self):
        """Synchronously dispatch everything pending (caller's thread)."""
        for key, reqs in self.pop_ready(force=True):
            self.run(key, reqs)
