"""Shape-bucketing request batcher.

N callers each asking for one right-hand side against the same resident
operator should cost ONE kernel launch, not N: requests are bucketed by
(handle, single-RHS shape, dtype), column-stacked into one (n, K)
right-hand side, solved once through the Session, and split back —
every *_solve_using_factor verb is column-independent, and dense
right-hand sides are tile-padded to the operator's nb, so a K≤nb batch
runs the SAME padded shape (hence the same compiled executable) as a
single request and returns bit-identical per-request results.

A bucket dispatches when it reaches ``max_batch`` or when its oldest
request has waited ``max_wait`` seconds (the serving deadline knob:
latency floor vs launch amortization). The Batcher itself owns no
thread — the Executor drives ``pop_ready``/``run``; ``flush`` exists
for synchronous callers and tests.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Dict, Hashable, List, Optional, Tuple

import numpy as np

from ..obs.tracing import NOOP_SPAN as _NOOP_SPAN
from .session import Session


@dataclasses.dataclass
class _Request:
    b: np.ndarray          # always 2-D (rows, 1..k) column block
    vector: bool           # original rank (reshape on completion)
    future: Future
    t_submit: float
    # obs span, opened at dispatch (parent: the batch span) and closed
    # at future resolution; None while tracing is off or pre-dispatch
    span: object = None


BucketKey = Tuple[Hashable, Tuple[int, ...], str]


class Batcher:
    """Coalesces same-operator/same-shape solve requests (see module
    docstring). Thread-safe; dispatch runs on the caller of ``run``."""

    def __init__(self, session: Session, max_batch: int = 32,
                 max_wait: float = 2e-3):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.session = session
        self.max_batch = max_batch
        self.max_wait = max_wait
        self._lock = threading.Lock()
        self._buckets: Dict[BucketKey, List[_Request]] = {}

    # -- submission --------------------------------------------------------

    def submit(self, handle: Hashable, b) -> Future:
        """Enqueue one solve request; resolves to the solution array
        with the same rank as ``b``."""
        b = np.asarray(b)
        vector = b.ndim == 1
        b2 = b[:, None] if vector else b
        key: BucketKey = (handle, tuple(b2.shape), str(b2.dtype))
        req = _Request(b2, vector, Future(), time.monotonic())
        self.session.metrics.inc("requests_total")
        with self._lock:
            self._buckets.setdefault(key, []).append(req)
        return req.future

    def pending(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._buckets.values())

    # -- readiness ---------------------------------------------------------

    def next_deadline(self) -> Optional[float]:
        """Earliest monotonic time any bucket must dispatch, or None."""
        with self._lock:
            oldest = [reqs[0].t_submit for reqs in self._buckets.values()
                      if reqs]
        if not oldest:
            return None
        return min(oldest) + self.max_wait

    def pop_ready(self, now: Optional[float] = None, force: bool = False
                  ) -> List[Tuple[BucketKey, List[_Request]]]:
        """Detach buckets that are full or past deadline (all of them
        when ``force``). Requests beyond max_batch stay queued."""
        now = time.monotonic() if now is None else now
        out: List[Tuple[BucketKey, List[_Request]]] = []
        with self._lock:
            for key in list(self._buckets):
                reqs = self._buckets[key]
                while (len(reqs) >= self.max_batch
                       or (reqs and force)
                       or (reqs and now - reqs[0].t_submit >= self.max_wait)):
                    take, rest = reqs[:self.max_batch], reqs[self.max_batch:]
                    out.append((key, take))
                    self._buckets[key] = reqs = rest
                if not reqs:
                    del self._buckets[key]
        return out

    # -- dispatch ----------------------------------------------------------

    def run(self, key: BucketKey, reqs: List[_Request]):
        """Solve one detached bucket: stack → one Session solve → split.
        Future resolution (including request latency metrics) happens
        here; exceptions propagate to the caller AND the unresolved
        futures are left pending so the caller can retry (see Executor).
        Idempotent over futures: already-done (resolved on an earlier
        attempt, or client-cancelled) requests are skipped, so a retry
        only covers what is still unresolved.

        Tracing: the batch span is the trace ROOT — N requests meet in
        one dispatch, and a tree has one root, so the per-request spans
        are parented onto the batch span (their queue wait rides along
        as the ``queue_s`` attribute, their end is future resolution);
        the Session's solve/factor/dispatch spans nest under the batch
        span via the contextvar scope."""
        handle = key[0]
        live = [r for r in reqs if not r.future.done()]
        if not live:
            return
        tr = self.session.tracer
        now = time.monotonic()
        bctx = (tr.span("serve.batch", handle=repr(handle),
                        batch_size=len(live), shape=list(key[1]),
                        dtype=key[2]) if tr.enabled else _NOOP_SPAN)
        with bctx as bspan:
            for r in live:
                # None unless this attempt re-runs a bucket whose spans
                # the Executor already closed (errored attempt) — each
                # attempt gets spans nested in ITS batch span
                if r.span is None:
                    r.span = tr.start_span(
                        "serve.request", parent=bspan, kind="request",
                        handle=repr(handle), shape=list(r.b.shape),
                        dtype=key[2], queue_s=now - r.t_submit)
            try:
                stacked = np.concatenate([r.b for r in live], axis=1)
                x = self.session.solve(handle, stacked)
            except Exception as e:
                # close this attempt's request spans INSIDE the batch
                # scope: the exception is about to close the batch span
                # via bctx.__exit__, and children ending after their
                # parent fail the Chrome-trace nesting validator
                for r in live:
                    tr.finish_span(r.span, error=e)
                raise
            m = self.session.metrics
            m.inc("batches_total")
            m.observe("batch_size", float(len(live)))
            done = time.monotonic()
            col = 0
            for r in live:
                w = r.b.shape[1]
                xi = x[:, col:col + w]
                col += w
                try:
                    r.future.set_result(xi[:, 0] if r.vector else xi)
                except InvalidStateError:
                    # client cancelled between our done() check and here
                    m.inc("cancelled_requests")
                    tr.finish_span(r.span, cancelled=True)
                    continue
                lat = done - r.t_submit
                m.observe("request_latency", lat)
                # total_s (submit -> resolve) is what the slow-request
                # log thresholds on — the client-visible latency
                tr.finish_span(r.span, total_s=lat)

    def flush(self):
        """Synchronously dispatch everything pending (caller's thread)."""
        for key, reqs in self.pop_ready(force=True):
            self.run(key, reqs)
