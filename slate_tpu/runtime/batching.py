"""Shape-bucketing request batcher.

N callers each asking for one right-hand side against the same resident
operator should cost ONE kernel launch, not N: requests are bucketed by
(handle, single-RHS shape, dtype), column-stacked into one (n, K)
right-hand side, solved once through the Session, and split back —
every *_solve_using_factor verb is column-independent, and dense
right-hand sides are tile-padded to the operator's nb, so a K≤nb batch
runs the SAME padded shape (hence the same compiled executable) as a
single request and returns bit-identical per-request results.

**Distinct-operator grouping (round 10).** Small-problem operators
(``Session`` op kinds ``lu_small``/``chol_small``) are additionally
grouped ACROSS handles: every request whose operator shares
(op, n, dtype) and whose rhs shares a shape lands in one bucket
regardless of which operator it targets, and the bucket dispatches as
ONE batched program pass (``Session.solve_small_batched`` — batched
factor for the cache misses, one batched solve over the stacked
resident factors) instead of B per-request programs. Results are
bit-identical to per-request dispatch because the batched kernels'
arithmetic is batch-independent (linalg/batched); a singular item
fails ITS future with the per-item info and leaves its bucket
neighbors' solutions untouched.

A bucket dispatches when it reaches ``max_batch`` or when its oldest
request has waited ``max_wait`` seconds (the serving deadline knob:
latency floor vs launch amortization). The Batcher itself owns no
thread — the Executor drives ``pop_ready``/``run``; ``flush`` exists
for synchronous callers and tests.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Dict, Hashable, List, Optional, Tuple

import numpy as np

from ..core.exceptions import SlateError
from ..obs.tracing import NOOP_SPAN as _NOOP_SPAN
from .session import Session


@dataclasses.dataclass
class _Request:
    b: np.ndarray          # always 2-D (rows, 1..k) column block
    vector: bool           # original rank (reshape on completion)
    future: Future
    t_submit: float
    # the operator this request targets (small-problem grouped buckets
    # hold requests against DISTINCT handles; same-operator buckets
    # carry it in the key too)
    handle: Hashable = None
    # obs span, opened at dispatch (parent: the batch span) and closed
    # at future resolution; None while tracing is off or pre-dispatch
    span: object = None


BucketKey = Tuple[Hashable, Tuple[int, ...], str]

# first element of a grouped small-problem bucket key — a private
# sentinel, so no user handle (which may be any hashable, including
# the string "small") can collide with it
_SMALL = object()


class Batcher:
    """Coalesces same-operator/same-shape solve requests (see module
    docstring). Thread-safe; dispatch runs on the caller of ``run``."""

    def __init__(self, session: Session, max_batch: int = 32,
                 max_wait: float = 2e-3, pad_widths: bool = False):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.session = session
        self.max_batch = max_batch
        self.max_wait = max_wait
        # pow2 width quantization (round 11): pad the stacked
        # right-hand side out to the next power of two with zero
        # columns before dispatch, so a varying coalesced width lowers
        # to O(log max_batch) distinct solve programs instead of one
        # per width — the knob that keeps a MESH session's expensive
        # sharded AOT compiles bounded. Per-request results are
        # untouched: every *_solve_using_factor verb is
        # column-independent, so the extra zero columns never feed the
        # real ones (and they are sliced off before futures resolve).
        self.pad_widths = pad_widths
        self._lock = threading.Lock()
        self._buckets: Dict[BucketKey, List[_Request]] = {}
        # incrementally-maintained backpressure state (round 12): the
        # submit hot path publishes gauges from these two counters
        # instead of scanning every bucket while holding the lock;
        # pop_ready recomputes them exactly from the queue
        self._depth = 0
        self._max_backlog = 0
        self._oldest: Optional[float] = None  # head submit time

    # -- submission --------------------------------------------------------

    def submit(self, handle: Hashable, b) -> Future:
        """Enqueue one solve request; resolves to the solution array
        with the same rank as ``b``. Small-problem operators are
        grouped across handles (module docstring): their bucket key is
        (op, n, dtype, rhs-shape), not the handle."""
        b = np.asarray(b)
        vector = b.ndim == 1
        b2 = b[:, None] if vector else b
        skey = self.session.small_group_key(handle)
        if skey is not None:
            key: BucketKey = (_SMALL,) + skey + (tuple(b2.shape),
                                                 str(b2.dtype))
        else:
            key = (handle, tuple(b2.shape), str(b2.dtype))
        req = _Request(b2, vector, Future(), time.monotonic(),
                       handle=handle)
        self.session.metrics.inc("requests_total")
        with self._lock:
            bucket = self._buckets.setdefault(key, [])
            bucket.append(req)
            # cheap incremental gauge publish (one batched metrics-
            # lock hold, no full-queue scan on the enqueue hot path);
            # oldest_request_age_s is as of the last queue transition
            # — pop_ready and backpressure() recompute it exactly
            self._depth += 1
            self._max_backlog = max(self._max_backlog, len(bucket))
            if self._oldest is None:
                self._oldest = req.t_submit  # only pops move it back
            self.session.metrics.set_gauges({
                "queue_depth": self._depth,
                "queued_buckets": len(self._buckets),
                "max_bucket_backlog": self._max_backlog,
                "oldest_request_age_s": req.t_submit - self._oldest,
            })
        return req.future

    def pending(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._buckets.values())

    # -- backpressure telemetry (round 12) ---------------------------------

    def _update_backpressure_locked(self, now: Optional[float] = None):
        """Caller holds the lock. Publish the queue's truth as gauges —
        before this, the only queue signal was the indirect ``queue_s``
        span attribute. Exact recompute, run on pops (the submit hot
        path publishes from the incremental counters instead — module
        state above), so a scrape between dispatches reads the state
        as of the last queue transition. Also resyncs the incremental
        counters."""
        now = time.monotonic() if now is None else now
        m = self.session.metrics
        depths = [len(v) for v in self._buckets.values() if v]
        self._depth = sum(depths)
        self._max_backlog = max(depths, default=0)
        self._oldest = min((reqs[0].t_submit
                            for reqs in self._buckets.values() if reqs),
                           default=None)
        m.set_gauges({
            "queue_depth": self._depth,
            "queued_buckets": len(depths),
            "max_bucket_backlog": self._max_backlog,
            "oldest_request_age_s": (0.0 if self._oldest is None
                                     else now - self._oldest),
        })

    def backpressure(self) -> dict:
        """Point-in-time queue state, per bucket (JSON-friendly: the
        /metrics gauges carry the aggregates; this is the labeled
        breakdown a debugger wants)."""
        now = time.monotonic()
        with self._lock:
            per_bucket = {
                repr(key): {"backlog": len(reqs),
                            "oldest_age_s": now - reqs[0].t_submit}
                for key, reqs in self._buckets.items() if reqs}
        return {
            "queue_depth": sum(v["backlog"] for v in per_bucket.values()),
            "queued_buckets": len(per_bucket),
            "oldest_request_age_s": max(
                (v["oldest_age_s"] for v in per_bucket.values()),
                default=0.0),
            "per_bucket": per_bucket,
        }

    # -- readiness ---------------------------------------------------------

    def next_deadline(self) -> Optional[float]:
        """Earliest monotonic time any bucket must dispatch, or None."""
        with self._lock:
            oldest = [reqs[0].t_submit for reqs in self._buckets.values()
                      if reqs]
        if not oldest:
            return None
        return min(oldest) + self.max_wait

    def pop_ready(self, now: Optional[float] = None, force: bool = False
                  ) -> List[Tuple[BucketKey, List[_Request]]]:
        """Detach buckets that are full or past deadline (all of them
        when ``force``). Requests beyond max_batch stay queued."""
        now = time.monotonic() if now is None else now
        out: List[Tuple[BucketKey, List[_Request]]] = []
        with self._lock:
            for key in list(self._buckets):
                reqs = self._buckets[key]
                while (len(reqs) >= self.max_batch
                       or (reqs and force)
                       or (reqs and now - reqs[0].t_submit >= self.max_wait)):
                    take, rest = reqs[:self.max_batch], reqs[self.max_batch:]
                    out.append((key, take))
                    self._buckets[key] = reqs = rest
                if not reqs:
                    del self._buckets[key]
            if out:
                self._update_backpressure_locked(now)
        return out

    # -- dispatch ----------------------------------------------------------

    def run(self, key: BucketKey, reqs: List[_Request]):
        """Solve one detached bucket: stack → one Session solve → split.
        Future resolution (including request latency metrics) happens
        here; exceptions propagate to the caller AND the unresolved
        futures are left pending so the caller can retry (see Executor).
        Idempotent over futures: already-done (resolved on an earlier
        attempt, or client-cancelled) requests are skipped, so a retry
        only covers what is still unresolved.

        Tracing: the batch span is the trace ROOT — N requests meet in
        one dispatch, and a tree has one root, so the per-request spans
        are parented onto the batch span (their queue wait rides along
        as the ``queue_s`` attribute, their end is future resolution);
        the Session's solve/factor/dispatch spans nest under the batch
        span via the contextvar scope."""
        if key and key[0] is _SMALL:
            return self._run_small(key, reqs)
        handle = key[0]
        live = [r for r in reqs if not r.future.done()]
        if not live:
            return
        tr = self.session.tracer
        now = time.monotonic()
        bctx = (tr.span("serve.batch", handle=repr(handle),
                        batch_size=len(live), shape=list(key[1]),
                        dtype=key[2]) if tr.enabled else _NOOP_SPAN)
        m = self.session.metrics
        with bctx as bspan:
            # exemplar join key: the batch's trace id (NOOP -> None)
            tid = getattr(bspan, "trace_id", None)
            for r in live:
                # None unless this attempt re-runs a bucket whose spans
                # the Executor already closed (errored attempt) — each
                # attempt gets spans nested in ITS batch span
                if r.span is None:
                    r.span = tr.start_span(
                        "serve.request", parent=bspan, kind="request",
                        handle=repr(handle), shape=list(r.b.shape),
                        dtype=key[2], queue_s=now - r.t_submit)
                # lifecycle stage 1 (round 12): submit -> dispatch start
                m.observe("stage_queue_wait", now - r.t_submit,
                          exemplar=tid)
            try:
                t_form = time.monotonic()
                stacked = np.concatenate([r.b for r in live], axis=1)
                cols = stacked.shape[1]
                if self.pad_widths:
                    # the shared pow2 quantum (also the batch-dim
                    # bucket of linalg/batched) — one definition, so
                    # the Batcher's padded widths can never drift
                    # from the bucketing the rest of the repo primes
                    from ..ops.blocked import bucket_pow2
                    w = bucket_pow2(cols, 1)
                    if w > cols:
                        stacked = np.concatenate(
                            [stacked, np.zeros((stacked.shape[0],
                                                w - cols),
                                               stacked.dtype)], axis=1)
                # lifecycle stage 2: stack + width-pad the bucket (one
                # observation per batch — formation is batch-scoped)
                m.observe("stage_batch_form", time.monotonic() - t_form,
                          exemplar=tid)
                # served_cols: only the CLIENT columns count as solves
                # — the padded zero columns are executed work (the
                # ledgers see them, split out as padding_waste_flops/
                # bytes — round 12) but not served requests. Passed
                # only when padding actually happened, so the
                # unpadded path keeps the bare solve(handle, b)
                # signature.
                if stacked.shape[1] != cols:
                    x = self.session.solve(handle, stacked,
                                           served_cols=cols)
                else:
                    x = self.session.solve(handle, stacked)
            except Exception as e:
                # close this attempt's request spans INSIDE the batch
                # scope: the exception is about to close the batch span
                # via bctx.__exit__, and children ending after their
                # parent fail the Chrome-trace nesting validator
                for r in live:
                    tr.finish_span(r.span, error=e)
                raise
            m.inc("batches_total")
            m.observe("batch_size", float(len(live)))
            done = time.monotonic()
            slo = self.session.slo
            meta = (self.session.op_meta(handle)
                    if slo is not None else None)
            col = 0
            for r in live:
                w = r.b.shape[1]
                xi = x[:, col:col + w]
                col += w
                try:
                    r.future.set_result(xi[:, 0] if r.vector else xi)
                except InvalidStateError:
                    # client cancelled between our done() check and here
                    m.inc("cancelled_requests")
                    tr.finish_span(r.span, cancelled=True)
                    continue
                lat = done - r.t_submit
                m.observe("request_latency", lat, exemplar=tid)
                if meta is not None:
                    slo.record_request(meta[0], meta[1], lat, ok=True)
                # total_s (submit -> resolve) is what the slow-request
                # log thresholds on — the client-visible latency
                tr.finish_span(r.span, total_s=lat)
            # lifecycle stage 5: solve done -> futures resolved (the
            # split/copy/notify reply cost, once per batch)
            m.observe("stage_reply", time.monotonic() - done,
                      exemplar=tid)

    def _run_small(self, key: BucketKey, reqs: List[_Request]):
        """Grouped small-problem dispatch: one bucket of DISTINCT-
        operator requests → ONE batched program pass through
        ``Session.solve_small_batched`` (batched factor for misses +
        one batched solve over the stacked factors). A singular item
        fails ITS OWN future with the per-item info (the SlateError the
        per-request path would have raised); its neighbors' solutions
        are bit-identical to what per-request dispatch produces."""
        # key = (_SMALL, op, n, op-dtype[, refine-policy], rhs-shape,
        # rhs-dtype): mixed entries (round 13) carry their RefinePolicy
        # in the group key so two policies never coalesce — read the
        # fixed head and tail, tolerate the optional middle
        op, n = key[1], key[2]
        shape, bdt = key[-2], key[-1]
        live = [r for r in reqs if not r.future.done()]
        if not live:
            return
        tr = self.session.tracer
        now = time.monotonic()
        bctx = (tr.span("serve.batch", op=op, n=n, grouped=True,
                        batch_size=len(live), shape=list(shape),
                        dtype=bdt) if tr.enabled else _NOOP_SPAN)
        m = self.session.metrics
        with bctx as bspan:
            tid = getattr(bspan, "trace_id", None)
            for r in live:
                if r.span is None:
                    r.span = tr.start_span(
                        "serve.request", parent=bspan, kind="request",
                        handle=repr(r.handle), shape=list(r.b.shape),
                        dtype=bdt, queue_s=now - r.t_submit)
                m.observe("stage_queue_wait", now - r.t_submit,
                          exemplar=tid)
            try:
                xs, infos = self.session.solve_small_batched(
                    [r.handle for r in live], [r.b for r in live])
            except Exception as e:
                for r in live:
                    tr.finish_span(r.span, error=e)
                raise
            m.inc("batches_total")
            m.observe("batch_size", float(len(live)))
            done = time.monotonic()
            slo = self.session.slo
            for i, r in enumerate(live):
                if infos[i] != 0:
                    err = SlateError(
                        f"Session: operator {r.handle!r} factorization "
                        f"failed (info={infos[i]})")
                    try:
                        r.future.set_exception(err)
                    except InvalidStateError:
                        m.inc("cancelled_requests")
                    if slo is not None:
                        slo.record_request(op, n, done - r.t_submit,
                                           ok=False)
                    tr.finish_span(r.span, error=err)
                    continue
                xi = xs[i]
                try:
                    r.future.set_result(xi[:, 0] if r.vector else xi)
                except InvalidStateError:
                    m.inc("cancelled_requests")
                    tr.finish_span(r.span, cancelled=True)
                    continue
                lat = done - r.t_submit
                m.observe("request_latency", lat, exemplar=tid)
                if slo is not None:
                    slo.record_request(op, n, lat, ok=True)
                tr.finish_span(r.span, total_s=lat)
            m.observe("stage_reply", time.monotonic() - done,
                      exemplar=tid)

    def flush(self):
        """Synchronously dispatch everything pending (caller's thread)."""
        for key, reqs in self.pop_ready(force=True):
            self.run(key, reqs)
