"""Serving metrics: counters + latency histograms for the runtime.

The reference ships coarse per-phase timers (the global ``timers`` map
filled by drivers, printed by the tester at --timer-level 2) and the SVG
trace timeline; a serving runtime needs the inference-stack versions of
those: monotonically increasing counters (solves, cache hits/misses,
evictions), latency histograms with percentile readout (p50/p99), and
derived rates (solves/sec, GFLOP/s, cache hit-rate) — exported as JSON
and as Prometheus text (``to_prometheus`` / the obs HTTP endpoint's
/metrics route) so a fleet scraper can ingest them.

Phases are recorded through ``utils.trace.phase`` so every runtime
measurement also lands in the existing Trace SVG timeline and the coarse
``trace.timers`` map — one clock, three views.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from typing import Dict, Optional

from ..utils import trace


class Histogram:
    """Latency histogram backed by a capped sample reservoir.

    Keeps exact count/sum/min/max plus the most recent ``cap`` samples
    for percentile queries — at serving rates the recent window is what
    p50/p99 should describe anyway (a day-old tail says nothing about
    current latency)."""

    __slots__ = ("cap", "count", "total", "vmin", "vmax", "_samples",
                 "exemplar")

    def __init__(self, cap: int = 8192):
        self.cap = cap
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = 0.0
        self._samples = collections.deque(maxlen=cap)
        # exemplar of the worst TAGGED observation so far: a trace-id
        # join key from histogram to trace (round 12 — the lifecycle
        # stage histograms pass the live request's trace id). Tracked
        # against the tagged maximum, not vmax: an untagged cold-start
        # spike recorded before tracing was enabled must not block the
        # join forever
        self.exemplar: Optional[Dict[str, float]] = None

    def observe(self, value: float, exemplar=None):
        self.count += 1
        self.total += value
        self.vmin = min(self.vmin, value)
        self.vmax = max(self.vmax, value)
        if exemplar is not None and (self.exemplar is None
                                     or value >= self.exemplar["value"]):
            self.exemplar = {"trace_id": exemplar, "value": value}
        self._samples.append(value)

    def percentile(self, q: float) -> float:
        """q in [0, 100]; nearest-rank over the retained window."""
        if not self._samples:
            return 0.0
        s = sorted(self._samples)
        idx = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
        return s[idx]

    def snapshot(self) -> Dict[str, float]:
        # min/max are None (JSON null) while empty: a fabricated 0.0
        # would be indistinguishable from a real zero-latency sample
        # (and `max: 0.0` read as "slowest observation was 0") — the
        # Prometheus renderer omits the null gauges entirely
        empty = self.count == 0
        return {
            "count": self.count,
            "sum": self.total,
            "min": None if empty else self.vmin,
            "max": None if empty else self.vmax,
            "mean": None if empty else self.total / self.count,
            "p50": self.percentile(50),
            "p99": self.percentile(99),
            "exemplar": dict(self.exemplar) if self.exemplar else None,
        }


class Metrics:
    """Thread-safe counter/histogram registry for one serving Session.

    Counter vocabulary (all monotone):
      solves_total, requests_total, batches_total, cache_hits,
      cache_misses, evictions, evicted_bytes, factors_total, retries,
      aot_compiles, flops_total (factor+solve work), solve_flops_total /
      factor_flops_total (the split — the derived gflops rate is
      solve_flops_total over solve_latency seconds, so amortized
      factorizations do not inflate it), budget_overflows,
      oom_risk_warnings, bytes_accessed_total, collective_bytes_total,
      padding_waste_flops / padding_waste_bytes (round 12: executed
      pow2-bucket/width padding split OUT of the useful-work counters),
      slo_breaches_total, watchdog_anomalies_total;
      round-14 reflexes/conservation: completed_requests /
      failed_requests_total / deadline_expired_total /
      shed_requests_total / admission_rejected_total (+ the existing
      cancelled_requests — together these partition requests_total,
      the chaos-soak conservation invariant; the one deliberate gap
      is a future the CLIENT cancelled while queued, which the
      runtime skips without re-resolving or counting — the round-6
      pinned convention), load_sheds_total,
      degraded_dispatches_total, breaker_trips_total /
      breaker_probes_total / breaker_closes_total /
      breaker_short_circuits / breaker_rejections_total,
      refine_demotions_total, faults_injected_total + fault:{kind};
      round-15 attribution (credited only while a Session carries an
      AttributionLedger, with grid-snapped values so the per-tenant
      cells sum to them bit-exactly — obs/attribution.py):
      device_seconds_total, queue_seconds_total,
      residency_byte_seconds_total;
      round-18 tenant isolation (runtime/tenancy.py):
      quota_rejections_total (a tenant over its own in-flight cap or
      flops/s rate, turned away counted — joins the conservation
      partition as the quota_rejected outcome),
      tenant_quota_evictions_total / tenant_quota_overflows (the
      per-tenant HBM sub-budget's LRU reflex), tenant_sheds_total
      (tenant-scoped burn-rate sheds), and the Fleet coordinator's
      fleet_migrations_total / fleet_migrations_warm /
      fleet_migrated_bytes / fleet_migration_aborts_total /
      fleet_migration_retries_total
    Histograms (seconds, except batch_size):
      solve_latency, factor_latency, request_latency, batch_size, and
      the round-12 request lifecycle stages — stage_queue_wait,
      stage_batch_form, stage_dispatch, stage_device_execute,
      stage_reply — each carrying the worst sample's exemplar trace-id
    Gauges (point-in-time, set not incremented):
      resident_bytes, peak_hbm_bytes, hbm_headroom — the Session's HBM
      truth (factor residency + largest program transient, round 9);
      round-12 backpressure: queue_depth, queued_buckets,
      oldest_request_age_s, max_bucket_backlog (Batcher),
      inflight_batches (Executor); bucket efficiency:
      width_bucket_efficiency / batch_bucket_efficiency (served ÷
      executed fraction of the last padded dispatch); slo_burn_rate:* /
      slo_breached:* and watchdog_* (obs/slo.py, obs/watchdog.py);
      round-14 reflexes: shedding_active, circuit_breakers_open;
      round-15 handle heat: handle_heat:{tenant}:{handle} — the
      EWMA access rate the placement snapshot ranks residents by;
      round-18 tenant isolation: tenant_quota_inflight:{tenant}
      (submitted-and-unresolved, the in-flight cap's live value),
      tenant_quota_resident_bytes:{tenant} /
      tenant_quota_hbm_headroom:{tenant} (sub-budget truth), and
      fair_share_deficit:{tenant} (the DRR scheduler's carried
      deficit — bounded by one quantum)
    """

    def __init__(self, clock=time.time):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = collections.defaultdict(float)
        self._hists: Dict[str, Histogram] = {}
        self._gauges: Dict[str, float] = {}
        # round 23: every gauge write is stamped at set time (the
        # injectable clock) — the history sampler records WHEN a value
        # was last true, not when it happened to be scraped
        self._gauge_ts: Dict[str, float] = {}
        self._clock = clock
        self._t0 = time.perf_counter()

    def inc(self, name: str, value: float = 1.0):
        with self._lock:
            self._counters[name] += value

    def get(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def set_gauge(self, name: str, value: float,
                  t: Optional[float] = None):
        """Point-in-time gauge (resident_bytes, hbm_headroom, ...):
        last write wins, rendered as a Prometheus gauge; the sample is
        timestamped (``t`` overrides the clock — tests and replayed
        snapshots)."""
        now = self._clock() if t is None else t
        with self._lock:
            self._gauges[name] = float(value)
            self._gauge_ts[name] = now

    def set_gauges(self, values: Dict[str, float],
                   t: Optional[float] = None):
        """Batch gauge write: one lock acquisition for N gauges — the
        Batcher's per-enqueue backpressure update uses this so the
        request hot path pays one metrics-lock hold, not four. All N
        share one timestamp (they were true together)."""
        now = self._clock() if t is None else t
        with self._lock:
            for name, value in values.items():
                self._gauges[name] = float(value)
                self._gauge_ts[name] = now

    def get_gauge(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._gauges.get(name, default)

    def drop_gauge(self, name: str):
        """Remove a gauge from the scrape surface (no error if absent).
        Round 15: per-handle heat gauges exist only while the handle is
        resident — eviction drops the gauge so handle churn cannot grow
        /metrics cardinality without bound."""
        with self._lock:
            self._gauges.pop(name, None)
            self._gauge_ts.pop(name, None)

    def observe(self, name: str, value: float, exemplar=None):
        """``exemplar`` (a trace id) tags the observation so the worst
        sample in a histogram stays joinable to its trace."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram()
            h.observe(value, exemplar=exemplar)

    def phase(self, name: str, hist: Optional[str] = None,
              tracer=None, **attrs):
        """Context manager: a trace phase whose elapsed time also lands
        in histogram ``hist`` (default: same name). With a ``tracer``
        (obs.tracing.Tracer) that is enabled, the phase is recorded as
        a structured SPAN instead — which itself feeds the legacy
        timers map and SVG timeline on finish, so no view is lost —
        with ``attrs`` attached; when tracing is off the span path
        costs one attribute check and no allocation."""
        return _MetricPhase(self, name, hist or name, tracer, attrs)

    # -- derived views -----------------------------------------------------

    @staticmethod
    def _derive(hits: float, misses: float, solves: float, flops: float,
                solve_seconds: float) -> Dict[str, float]:
        """One definition of the serving headline formulas, shared by
        the accessor methods and the JSON snapshot — so a counting-
        convention change cannot diverge the two."""
        total = hits + misses
        return {
            "cache_hit_rate": hits / total if total else 0.0,
            "solves_per_sec": (solves / solve_seconds
                               if solve_seconds > 0 else 0.0),
            "gflops": (flops / solve_seconds / 1e9
                       if solve_seconds > 0 else 0.0),
        }

    def _derived_now(self) -> Dict[str, float]:
        with self._lock:
            h = self._hists.get("solve_latency")
            return self._derive(
                self._counters.get("cache_hits", 0.0),
                self._counters.get("cache_misses", 0.0),
                self._counters.get("solves_total", 0.0),
                self._counters.get("solve_flops_total", 0.0),
                h.total if h is not None else 0.0)

    def cache_hit_rate(self) -> float:
        return self._derived_now()["cache_hit_rate"]

    def solves_per_sec(self) -> float:
        """Throughput over accumulated device-solve time (dispatch+block),
        not wall time — the bench driver reports wall-clock separately."""
        return self._derived_now()["solves_per_sec"]

    def gflops(self) -> float:
        return self._derived_now()["gflops"]

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            hists = {k: h.snapshot() for k, h in self._hists.items()}
            gauges = dict(self._gauges)
            gauge_ts = dict(self._gauge_ts)
            uptime = time.perf_counter() - self._t0
        # derived serving headline numbers (computed outside the lock
        # from the consistent copies above)
        solve = hists.get("solve_latency", {})
        return {
            "uptime_s": uptime,
            "counters": counters,
            "histograms": hists,
            "gauges": gauges,
            "gauge_ts": gauge_ts,
            "derived": self._derive(
                counters.get("cache_hits", 0.0),
                counters.get("cache_misses", 0.0),
                counters.get("solves_total", 0.0),
                counters.get("solve_flops_total", 0.0),
                solve.get("sum", 0.0)),
        }

    def to_json(self, path: Optional[str] = None, indent: int = 2) -> str:
        """Serialize the snapshot; writes to ``path`` when given."""
        text = json.dumps(self.snapshot(), indent=indent, sort_keys=True)
        if path is not None:
            with open(path, "w") as f:
                f.write(text + "\n")
        return text

    def to_prometheus(self, path: Optional[str] = None) -> str:
        """Prometheus text exposition of the snapshot (plus the process
        FLOP ledger) — the /metrics payload; see obs/exposition.py."""
        from ..obs.exposition import render_prometheus
        text = render_prometheus(self.snapshot())
        if path is not None:
            with open(path, "w") as f:
                f.write(text)
        return text


class _MetricPhase:
    """trace.phase that feeds its elapsed time into a Metrics histogram
    — upgraded to a structured span when an enabled obs Tracer is
    bound (the span's finish bridges back to the legacy views)."""

    __slots__ = ("_metrics", "_hist", "_phase", "_span_ctx", "_span",
                 "elapsed")

    def __init__(self, metrics: Metrics, name: str, hist: str,
                 tracer=None, attrs=None):
        self._metrics = metrics
        self._hist = hist
        self.elapsed = 0.0
        if tracer is not None and tracer.enabled:
            self._phase = None
            self._span_ctx = tracer.span(name, **(attrs or {}))
        else:
            self._phase = trace.phase(name)
            self._span_ctx = None

    def __enter__(self):
        if self._span_ctx is not None:
            self._span = self._span_ctx.__enter__()
        else:
            self._phase.__enter__()
        return self

    @property
    def span(self):
        """The live span (None on the legacy path) — for attaching
        attributes discovered mid-phase (cache hit, batch size)."""
        return getattr(self, "_span", None)

    def __exit__(self, *exc):
        if self._span_ctx is not None:
            self._span_ctx.__exit__(*exc)
            self.elapsed = self._span.duration or 0.0
        else:
            self._phase.__exit__(*exc)
            self.elapsed = self._phase.elapsed
        self._metrics.observe(self._hist, self.elapsed)
        return False
