"""Multi-tenant isolation: declarative quotas + deficit-weighted fairness.

ROADMAP item 1's last gap: rounds 12–17 gave the fleet *senses*
(per-tenant attribution cells, handle heat, placement snapshots),
global *reflexes* (shedding, breakers, deadlines), and *failover*
(checkpoint/restore, replication) — but nothing stopped one tenant
from starving every other: Batcher dispatch was FIFO, the HBM budget
one global pool, and ShedPolicy shed by cost, never by who was
overloading the system. SLATE never needed this layer (an MPI job owns
its allocation; the reference's 2D-block-cyclic world has one user); a
"millions of users" serving fleet cannot live without it.

* :class:`TenantPolicy` — one tenant's declarative limits: a per-tenant
  HBM sub-budget over RESIDENT factors (enforced at the Session's
  factor-insert seam with per-tenant LRU eviction, so tenant A's
  pressure can never evict tenant B's resident), an in-flight request
  cap and an optional model-flops/s rate (both enforced at
  ``Batcher.submit`` — a counted :class:`~.faults.QuotaExceeded`
  rejection, never a silent drop; the round-14 conservation partition
  grows a ``quota_rejected`` outcome), and the fair-share ``weight``
  the scheduler serves it at.
* :class:`TenantTable` — the tenant -> policy map a Session/Batcher
  consults (``default`` covers unlisted tenants; ``None`` default =
  unlisted tenants are unconstrained at weight 1.0).
* :class:`DeficitScheduler` — deficit-weighted round-robin over
  per-tenant ready queues, replacing the Batcher's FIFO bucket pop.
  Pure counter math (no clock), so the starvation bound is
  hand-pinnable: see :meth:`DeficitScheduler.order`.
* :class:`TokenBucket` — the optional flops/s rate limiter (injectable
  clock, so refill math is pinnable without sleeping).

Disabled (``tenant_policies=None``, the default) every seam is one
``is None`` check and allocates nothing — the round-8 discipline,
extended here by test. Stdlib-only and jax-free (the faults.py import
rule: the decision math adds no import weight to the runtime)."""

from __future__ import annotations

import dataclasses
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple, TypeVar

T = TypeVar("T")


@dataclasses.dataclass(frozen=True)
class TenantPolicy:
    """One tenant's declarative isolation limits.

    ``max_resident_bytes``: per-tenant HBM sub-budget over this
    tenant's RESIDENT factors (per-chip charge, the round-11
    convention) — enforced with per-tenant LRU eviction at the
    Session's factor-insert seam; ``None`` = only the global budget
    bounds it. ``max_in_flight``: cap on submitted-but-unresolved
    requests — the (B+1)-th submit is turned away at the door with a
    counted :class:`~.faults.QuotaExceeded` (``quota_rejections_total``
    moves, the conservation partition's ``quota_rejected`` outcome
    records it; never a silent drop). ``weight``: the deficit-round-
    robin share — a weight-2 tenant gets twice the dispatch slots of a
    weight-1 tenant under contention (idle capacity always flows to
    whoever has traffic — DRR is work-conserving). ``flops_per_s``:
    optional admission rate in model flops (the round-9 recompute-cost
    vocabulary) metered by a :class:`TokenBucket` with ``burst_s``
    seconds of rate as depth."""

    max_resident_bytes: Optional[int] = None
    max_in_flight: Optional[int] = None
    weight: float = 1.0
    flops_per_s: Optional[float] = None
    burst_s: float = 1.0

    def __post_init__(self):
        if not self.weight > 0.0:
            raise ValueError(
                f"TenantPolicy: weight must be > 0, got {self.weight}")
        if self.max_in_flight is not None and self.max_in_flight < 1:
            raise ValueError("TenantPolicy: max_in_flight must be >= 1, "
                             f"got {self.max_in_flight}")
        if self.max_resident_bytes is not None \
                and self.max_resident_bytes < 0:
            raise ValueError("TenantPolicy: max_resident_bytes must be "
                             f">= 0, got {self.max_resident_bytes}")
        if self.flops_per_s is not None and not self.flops_per_s > 0.0:
            raise ValueError("TenantPolicy: flops_per_s must be > 0, "
                             f"got {self.flops_per_s}")
        if not self.burst_s > 0.0:
            raise ValueError(f"TenantPolicy: burst_s must be > 0, "
                             f"got {self.burst_s}")

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class TenantTable:
    """tenant -> :class:`TenantPolicy` with an optional default for
    unlisted tenants. Immutable after construction (the Session and
    Batcher read it lock-free, the ``_Operator``-fields discipline)."""

    def __init__(self, policies: Optional[Dict[str, TenantPolicy]] = None,
                 default: Optional[TenantPolicy] = None):
        self._policies = {str(t): p for t, p in (policies or {}).items()}
        for t, p in self._policies.items():
            if not isinstance(p, TenantPolicy):
                raise TypeError(f"TenantTable: policy for {t!r} is "
                                f"{type(p).__name__}, not TenantPolicy")
        if default is not None and not isinstance(default, TenantPolicy):
            raise TypeError("TenantTable: default must be a TenantPolicy")
        self.default = default

    def policy(self, tenant: str) -> Optional[TenantPolicy]:
        return self._policies.get(str(tenant), self.default)

    def weight(self, tenant: str) -> float:
        pol = self.policy(tenant)
        return 1.0 if pol is None else pol.weight

    def tenants(self) -> List[str]:
        return sorted(self._policies)

    def to_dict(self) -> dict:
        return {
            "policies": {t: p.to_dict()
                         for t, p in sorted(self._policies.items())},
            "default": (None if self.default is None
                        else self.default.to_dict()),
        }


def as_table(policies) -> Optional[TenantTable]:
    """Coerce the ``tenant_policies=`` argument: None passes through
    (the disabled path), a TenantTable is taken as-is, a plain dict of
    policies builds one."""
    if policies is None or isinstance(policies, TenantTable):
        return policies
    if isinstance(policies, dict):
        return TenantTable(policies)
    raise TypeError("tenant_policies must be None, a TenantTable, or a "
                    f"{{tenant: TenantPolicy}} dict, got "
                    f"{type(policies).__name__}")


class TokenBucket:
    """Model-flops admission meter (one per rate-limited tenant).

    Classic token bucket: ``rate`` tokens/s refill up to ``burst``
    depth; :meth:`admit` debits ``cost`` tokens or refuses. The clock
    is injectable so refill math is pinnable without sleeping. NOT
    thread-safe on its own — the Batcher calls it under its queue
    lock (the quota seam's lock)."""

    __slots__ = ("rate", "burst", "tokens", "_last", "_clock")

    def __init__(self, rate: float, burst: float,
                 clock=time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)  # start full: a fresh tenant bursts
        self._clock = clock
        self._last = clock()

    def admit(self, cost: float, now: Optional[float] = None) -> bool:
        now = self._clock() if now is None else now
        self.tokens = min(self.burst,
                          self.tokens + max(now - self._last, 0.0)
                          * self.rate)
        self._last = now
        if cost > self.tokens:
            return False
        self.tokens -= cost
        return True


class DeficitScheduler:
    """Deficit-weighted round-robin over per-tenant ready buckets.

    The Batcher hands :meth:`order` the buckets one ``pop_ready`` call
    detached (each tagged with its tenant and its cost = live request
    count) and dispatches in the returned order. Deficit counters
    persist ACROSS calls, so long-run dispatch shares converge to the
    weights even though each call only reorders its own snapshot.

    **Starvation bound (hand-pinned by tests/test_tenancy.py).**
    Classic DRR: each round every backlogged tenant's deficit grows by
    ``quantum * weight`` and it emits head buckets while the deficit
    covers their cost. The quantum is the snapshot's max bucket cost,
    so a weight-w tenant emits its head bucket after at most
    ``ceil(cost_head / (quantum * w))`` rounds, and in each round any
    OTHER tenant j emits at most ``quantum * w_j / cost_min + 1``
    buckets — so the victim's head bucket is dispatched after a number
    of foreign buckets bounded by the weights, INDEPENDENT of the
    aggressor's backlog depth. FIFO has no such bound: the victim
    waits behind the aggressor's entire arrival history.

    A tenant's carried deficit is bounded by the snapshot quantum (it
    only grows while the tenant is backlogged, and the growth round
    immediately spends it down below the head cost), so an idle tenant
    cannot bank credit and burst past its weight later. The round-robin
    start rotates one tenant per call, so no tenant owns the "first
    emitted" slot structurally. Pure counter math, no clock,
    stdlib-only."""

    def __init__(self, table: TenantTable):
        self.table = table
        # tenant -> carried deficit (insertion order = round-robin
        # order; new tenants join at the tail, the order rotates one
        # step per order() call)
        self._deficit: "OrderedDict[str, float]" = OrderedDict()

    def order(self, buckets: Sequence[Tuple[str, int, T]]) -> List[T]:
        """DRR dispatch order for one snapshot of ready buckets:
        ``(tenant, cost, item)`` triples in, items out. Every item is
        returned (detached buckets must all dispatch — fairness is
        WHO GOES FIRST, the latency lever); the order interleaves
        tenants by weighted deficit instead of arrival."""
        if not buckets:
            return []
        queues: "OrderedDict[str, List[Tuple[int, T]]]" = OrderedDict()
        for tenant, cost, item in buckets:
            queues.setdefault(str(tenant), []).append(
                (max(int(cost), 1), item))
        for tenant in queues:
            self._deficit.setdefault(tenant, 0.0)
        if len(queues) == 1:
            # single-tenant snapshot: FIFO is DRR
            (q,) = queues.values()
            return [item for _, item in q]
        quantum = float(max(c for c, _ in
                            (p for q in queues.values() for p in q)))
        out: List[T] = []
        # visit in the persistent round-robin order (the deficit
        # dict's insertion order), carrying deficits between calls
        while queues:
            for tenant in list(self._deficit):
                q = queues.get(tenant)
                if not q:
                    continue
                self._deficit[tenant] += quantum * \
                    self.table.weight(tenant)
                while q and q[0][0] <= self._deficit[tenant]:
                    cost, item = q.pop(0)
                    self._deficit[tenant] -= cost
                    out.append(item)
                if not q:
                    # bounded banked credit: a drained tenant carries
                    # at most one quantum of deficit into the next
                    # snapshot (without the cap, a high-weight tenant
                    # draining tiny buckets would bank credit without
                    # bound call over call)
                    self._deficit[tenant] = min(self._deficit[tenant],
                                                quantum)
                    del queues[tenant]
        # prune tenants that are absent from this snapshot and carry
        # no deficit: tenant strings are client input, and the RR
        # state must not grow with tenant-string churn (the caller
        # drops the matching gauges — the round-15 cardinality
        # discipline)
        seen = {str(t) for t, _, _ in buckets}
        for t in [t for t, d in self._deficit.items()
                  if d == 0.0 and t not in seen]:
            del self._deficit[t]
        # rotate the round-robin start so the same tenant is not
        # structurally first in every snapshot
        if len(self._deficit) > 1:
            first, val = next(iter(self._deficit.items()))
            del self._deficit[first]
            self._deficit[first] = val
        return out

    def deficits(self) -> Dict[str, float]:
        """Point-in-time carried deficits (the ``fair_share_deficit``
        gauge source)."""
        return dict(self._deficit)
