from .generate import generate_matrix, random_spd
from . import random
