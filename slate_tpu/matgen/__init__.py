from .generate import cond_targeted, generate_matrix, random_spd
from . import random
