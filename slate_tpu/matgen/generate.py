"""Test-matrix generation (reference: matgen/ library, slate_matgen).

Reference entry point: generate_matrix(MatrixParams, A) with ~40 kinds
(matgen/generate_matrix_utils.cc:64-136; type builders
generate_type_{rand,svd,heev,geev}.hh; spectra in generate_sigma.hh).

Here: ``generate_matrix(kind, m, n, ...)`` returns a dense jax array (wrap
with core.from_dense to distribute). Determinism/distribution-independence
comes from slate_tpu.matgen.random (counter-based, logical-shape keyed).

Supported kind grammar (subset mirroring the reference):
  zeros | ones | identity | jordan | minij | hilb | gcdmat | toeppen
  rand | rands | randn | randb                    (+ _dominant suffix)
  diag^{spectrum} | svd_{spectrum} | heev_{spectrum} | poev_{spectrum}
with spectrum ∈ {logrand, arith, geo, cluster0, cluster1, rarith, rgeo,
rcluster0, rcluster1, specified} and condition number ``cond``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core.exceptions import SlateError
from . import random as rnd


def _spectrum(kind: str, n: int, cond: float, dtype, seed: int) -> jax.Array:
    """Singular/eigen-value profiles Σ (generate_sigma.hh analog).

    All profiles have σ₁ = 1, σₙ = 1/cond (before random sign for 'r'
    variants). Random profiles are keyed on the caller's seed, like the
    reference matgen (matgen/random.cc keys everything on params.seed)."""
    real = jnp.finfo(dtype).dtype
    i = jnp.arange(n, dtype=real)
    inv = jnp.asarray(1.0 / cond, real)
    if kind in ("logrand",):
        # log-uniform in [1/cond, 1]
        u = jax.random.uniform(jax.random.fold_in(jax.random.key(seed), 1),
                               (n,), real)
        sig = jnp.exp(u * jnp.log(inv))
    elif kind in ("arith",):
        sig = 1.0 - i / max(n - 1, 1) * (1.0 - inv)
    elif kind in ("geo",):
        sig = inv ** (i / max(n - 1, 1))
    elif kind in ("cluster0",):  # {1, 1/cond, ..., 1/cond}
        sig = jnp.where(i == 0, 1.0, inv)
    elif kind in ("cluster1",):  # {1, ..., 1, 1/cond}
        sig = jnp.where(i == n - 1, inv, 1.0)
    elif kind.startswith("r") and kind[1:] in ("logrand", "arith", "geo",
                                               "cluster0", "cluster1"):
        sig = _spectrum(kind[1:], n, cond, dtype, seed)
        sign = jnp.where(
            jax.random.bernoulli(jax.random.fold_in(jax.random.key(seed), 2),
                                 0.5, (n,)), 1.0, -1.0
        ).astype(real)
        sig = sig * sign
    else:
        raise SlateError(f"unknown spectrum '{kind}'")
    return sig.astype(real)


def _random_orthogonal(seed: int, n: int, dtype) -> jax.Array:
    """Haar-ish orthogonal/unitary via QR of a Gaussian (the reference
    applies random Householder reflectors, generate_type_svd.hh — QR of a
    Gaussian is the standard equivalent)."""
    g = rnd.normal(seed, n, n, dtype)
    q, r = jnp.linalg.qr(g)
    # fix signs for determinism
    d = jnp.diagonal(r)
    ph = jnp.where(d == 0, jnp.ones((), d.dtype), d / jnp.abs(d))
    return q * jnp.conj(ph)[None, :]


def generate_matrix(kind: str, m: int, n: Optional[int] = None,
                    dtype=jnp.float32, seed: int = 42,
                    cond: Optional[float] = None,
                    condD: Optional[float] = None) -> jax.Array:
    """Dense (m × n) test matrix of the given kind.

    ``condD``: two-sided diagonal scaling A ← D·A·D with D log-spaced
    over [condD^-½, condD^½] — the reference's condD knob
    (matgen/generate_matrix_utils.cc:64-136), which grades row/column
    norms to stress scaling-sensitive paths (equilibration, pivoting).
    """
    a = _generate_unscaled(kind, m, n, dtype, seed, cond)
    if condD is not None and condD != 1.0:
        nn = a.shape
        real = jnp.finfo(dtype).dtype
        h = 0.5 * jnp.log(jnp.asarray(condD, real))
        dr = jnp.exp(jnp.linspace(-h, h, nn[0])).astype(dtype)
        dc = jnp.exp(jnp.linspace(-h, h, nn[1])).astype(dtype)
        a = dr[:, None] * a * dc[None, :]
    return a


def _generate_unscaled(kind: str, m: int, n: Optional[int],
                       dtype, seed: int, cond: Optional[float]) -> jax.Array:
    n = n if n is not None else m
    k = min(m, n)
    if cond is None:
        cond = 1.0e4
    base, _, spec = kind.partition("_")

    if kind == "zeros" or kind == "zero":
        return jnp.zeros((m, n), dtype)
    if kind == "ones" or kind == "one":
        return jnp.ones((m, n), dtype)
    if kind == "identity":
        return jnp.eye(m, n, dtype=dtype)
    if kind == "jordan":
        return jnp.eye(m, n, dtype=dtype) + jnp.eye(m, n, k=1, dtype=dtype)
    if kind == "minij":
        i = jnp.arange(1, m + 1)[:, None]
        j = jnp.arange(1, n + 1)[None, :]
        return jnp.minimum(i, j).astype(dtype)
    if kind == "hilb":
        i = jnp.arange(m)[:, None]
        j = jnp.arange(n)[None, :]
        return (1.0 / (i + j + 1)).astype(dtype)
    if kind == "gcdmat":
        i = jnp.arange(1, m + 1)[:, None]
        j = jnp.arange(1, n + 1)[None, :]
        return jnp.gcd(i, j).astype(dtype)
    if kind == "toeppen":
        # pentadiagonal Toeplitz [1, -10, 0, 10, 1]
        a = jnp.zeros((m, n), dtype)
        for off, v in ((-2, 1.0), (-1, -10.0), (1, 10.0), (2, 1.0)):
            a = a + v * jnp.eye(m, n, k=off, dtype=dtype)
        return a

    dominant = kind.endswith("_dominant")
    rkind = base
    if rkind in ("rand", "rands", "randn", "randb"):
        gen = {"rand": rnd.uniform, "rands": rnd.uniform_signed,
               "randn": rnd.normal, "randb": rnd.binary}[rkind]
        a = gen(seed, m, n, dtype)
        if dominant:
            a = a + k * jnp.eye(m, n, dtype=dtype)
        return a

    if base == "diag":
        sig = _spectrum(spec or "logrand", k, cond, dtype, seed)
        return jnp.zeros((m, n), dtype).at[jnp.arange(k), jnp.arange(k)].set(
            sig.astype(dtype))

    if base == "svd":
        sig = _spectrum(spec or "logrand", k, cond, dtype, seed)
        u = _random_orthogonal(seed, m, dtype)[:, :k]
        v = _random_orthogonal(seed + 1, n, dtype)[:, :k]
        return (u * sig[None, :].astype(dtype)) @ jnp.conj(v).T

    if base in ("heev", "syev"):
        sig = _spectrum(spec or "logrand", k, cond, dtype, seed)
        q = _random_orthogonal(seed, n, dtype)
        a = (q * sig[None, :].astype(dtype)) @ jnp.conj(q).T
        return 0.5 * (a + jnp.conj(a).T)

    if base == "poev":
        sig = jnp.abs(_spectrum(spec or "logrand", k, cond, dtype, seed))
        q = _random_orthogonal(seed, n, dtype)
        a = (q * sig[None, :].astype(dtype)) @ jnp.conj(q).T
        return 0.5 * (a + jnp.conj(a).T)

    if base == "geev":
        # nonsymmetric with prescribed eigenvalues (reference
        # generate_type_geev.hh): A = V·Λ·V⁻¹ with a well-conditioned
        # nonorthogonal V = I + ½·strict_lower(G)/√n
        lam = _spectrum(spec or "logrand", n, cond, dtype, seed)
        g = rnd.normal(seed + 3, n, n, dtype)
        v = jnp.eye(n, dtype=dtype) + 0.5 * jnp.tril(g, -1) / jnp.sqrt(
            jnp.asarray(float(n), jnp.finfo(dtype).dtype)).astype(dtype)
        # A = V Λ V⁻¹  via  solve(Vᵀ, (V Λ)ᵀ)ᵀ
        vl = v * lam[None, :].astype(dtype)
        return jnp.linalg.solve(v.T, vl.T).T

    raise SlateError(f"unknown matrix kind '{kind}'")


def random_spd(m: int, nb_unused: int = 0, dtype=jnp.float32, seed: int = 0,
               ) -> jax.Array:
    """Well-conditioned SPD/HPD matrix: A = G·Gᴴ/m + I (the standard posv
    tester input; reference test/matrix_params)."""
    g = rnd.normal(seed, m, m, dtype)
    a = g @ jnp.conj(g).T / m + jnp.eye(m, dtype=dtype)
    return 0.5 * (a + jnp.conj(a).T)
