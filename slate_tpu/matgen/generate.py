"""Test-matrix generation (reference: matgen/ library, slate_matgen).

Reference entry point: generate_matrix(MatrixParams, A) with ~40 kinds
(matgen/generate_matrix_utils.cc:64-136; entry formulas in
generate_matrix_ge.cc:80-465; type builders generate_type_{rand,svd,
heev}.hh; spectra in generate_sigma.hh).

Here: ``generate_matrix(kind, m, n, ...)`` returns a dense jax array (wrap
with core.from_dense to distribute). Determinism/distribution-independence
comes from slate_tpu.matgen.random (counter-based, logical-shape keyed).

Supported kind grammar (mirroring the reference):
  zeros | ones | identity | ij | jordan | jordanT | chebspec | circul |
  fiedler | gfpp | kms | orthog | riemann | ris | zielkeNS | minij |
  hilb | frank | lehmer | lotkin | redheff | triw | pei | tridiag |
  toeppen | parter | moler | cauchy | chow | clement | gcdmat
  rand | rands | randn | randb | randr             (+ modifiers)
  diag^ | svd^ | poev^ | spd^ | heev^ | syev^ | geev^
with ^spectrum ∈ {logrand, arith, geo, cluster0, cluster1, rarith, rgeo,
rcluster0, rcluster1, rand, rands, randn, specified} and condition
number ``cond``; scaling suffixes _ufl/_ofl/_small/_large; modifiers
_dominant and _zerocolN / _zerocolFRAC; condD row/col grading (column
scaling A·D for svd kinds, two-sided D·A·D for heev/poev — the
reference's generate_type_svd.hh:159-196 / generate_type_heev.hh:114-139
semantics, with the same log-uniform random D).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.exceptions import SlateError
from . import random as rnd

_SPECTRA = ("logrand", "arith", "geo", "cluster0", "cluster1",
            "rlogrand", "rarith", "rgeo", "rcluster0", "rcluster1",
            "rand", "rands", "randn", "specified")
_SCALINGS = ("ufl", "ofl", "small", "large")
_SPECTRAL_BASES = ("diag", "svd", "heev", "syev", "poev", "spd", "geev")


def _spectrum(kind: str, n: int, cond: float, dtype, seed: int,
              sigma=None) -> jax.Array:
    """Singular/eigen-value profiles Σ (generate_sigma.hh analog).

    All deterministic profiles have σ₁ = 1, σₙ = 1/cond (before random
    sign for 'r' variants). Random profiles are keyed on the caller's
    seed, like the reference matgen."""
    real = jnp.finfo(dtype).dtype
    i = jnp.arange(n, dtype=real)
    inv = jnp.asarray(1.0 / cond, real)
    if kind == "logrand":
        # log-uniform in [1/cond, 1]
        u = jax.random.uniform(jax.random.fold_in(jax.random.key(seed), 1),
                               (n,), real)
        sig = jnp.exp(u * jnp.log(inv))
    elif kind == "arith":
        sig = 1.0 - i / max(n - 1, 1) * (1.0 - inv)
    elif kind == "geo":
        sig = inv ** (i / max(n - 1, 1))
    elif kind == "cluster0":  # {1, 1/cond, ..., 1/cond}
        sig = jnp.where(i == 0, 1.0, inv)
    elif kind == "cluster1":  # {1, ..., 1, 1/cond}
        sig = jnp.where(i == n - 1, inv, 1.0)
    elif kind == "rand":
        sig = jax.random.uniform(jax.random.fold_in(jax.random.key(seed), 3),
                                 (n,), real)
    elif kind == "rands":
        sig = jax.random.uniform(jax.random.fold_in(jax.random.key(seed), 4),
                                 (n,), real, minval=-1.0, maxval=1.0)
    elif kind == "randn":
        sig = jax.random.normal(jax.random.fold_in(jax.random.key(seed), 5),
                                (n,), real)
    elif kind == "specified":
        if sigma is None:
            raise SlateError("spectrum 'specified' needs sigma=")
        sig = jnp.asarray(sigma, real)
        if sig.shape != (n,):
            raise SlateError(f"sigma must have shape ({n},)")
    elif kind.startswith("r") and kind[1:] in ("arith", "geo", "cluster0",
                                               "cluster1", "logrand"):
        sig = _spectrum(kind[1:], n, cond, dtype, seed)[::-1]
        # classic 'r' variants ALSO randomize signs in the reference's
        # heev use (rand_sign); plain reversal for svd keeps σ ≥ 0 —
        # sign randomization belongs to heev kinds and is applied there
    else:
        raise SlateError(f"unknown spectrum '{kind}'")
    return sig.astype(real)


def _random_orthogonal(seed: int, n: int, dtype) -> jax.Array:
    """Haar-ish orthogonal/unitary via QR of a Gaussian (the reference
    applies random Householder reflectors, generate_type_svd.hh — QR of a
    Gaussian is the standard equivalent)."""
    g = rnd.normal(seed, n, n, dtype)
    q, r = jnp.linalg.qr(g)
    # fix signs for determinism
    d = jnp.diagonal(r)
    ph = jnp.where(d == 0, jnp.ones((), d.dtype), d / jnp.abs(d))
    return q * jnp.conj(ph)[None, :]


def _cond_d_vector(condD: float, n: int, dtype, seed: int) -> jax.Array:
    """The reference's condD scaling vector: D_i = exp(u_i · log condD),
    u ~ U(0,1) — log-uniform in [1, condD] (generate_type_svd.hh:167)."""
    real = jnp.finfo(dtype).dtype
    u = jax.random.uniform(jax.random.fold_in(jax.random.key(seed), 9),
                           (n,), real)
    return jnp.exp(u * jnp.log(jnp.asarray(condD, real))).astype(dtype)


def generate_matrix(kind: str, m: int, n: Optional[int] = None,
                    dtype=jnp.float32, seed: int = 42,
                    cond: Optional[float] = None,
                    condD: Optional[float] = None,
                    sigma=None) -> jax.Array:
    """Dense (m × n) test matrix of the given kind (see module doc).

    ``condD`` grades row/column norms to stress scaling-sensitive paths
    (equilibration, pivoting): svd kinds get column scaling A·D, heev/
    poev kinds get the two-sided D·A·D, matching the reference
    (generate_type_svd.hh:159-196, generate_type_heev.hh:114-139).
    ``sigma``: the user-specified spectrum for ^specified kinds.
    """
    base = kind.split("_")[0]
    a = _generate_unscaled(kind, m, n, dtype, seed, cond, sigma)
    if condD is not None and condD != 1.0:
        if base in ("heev", "syev", "poev", "spd"):
            d = _cond_d_vector(condD, a.shape[0], dtype, seed)
            a = d[:, None] * a * d[None, :]
        elif base in ("svd", "gesvd", "rand", "rands", "randn", "randb",
                      "randr", "diag"):
            d = _cond_d_vector(condD, a.shape[1], dtype, seed)
            a = a * d[None, :]
        # other kinds ignore condD (the reference warns; we silently
        # no-op to stay functional under sweeps)
    return a


def _entrywise(m, n, dtype, fn):
    """A[i, j] = fn(i, j) on 0-based index grids (the reference's
    entry_type lambdas, generate_matrix_ge.cc:80-465)."""
    i = jnp.arange(m)[:, None]
    j = jnp.arange(n)[None, :]
    return fn(i, j).astype(dtype)


def _generate_unscaled(kind: str, m: int, n: Optional[int],
                       dtype, seed: int, cond: Optional[float],
                       sigma=None) -> jax.Array:
    n = n if n is not None else m
    k = min(m, n)
    if cond is None:
        cond = 1.0e4
    parts = kind.split("_")
    base = parts[0]
    mods = parts[1:]

    # peel scaling/modifier suffixes (reference decode_matrix); unknown
    # suffixes raise — a typo must not silently become the default
    # logrand spectrum (it would turn a stress matrix benign)
    scaling = None
    dominant = False
    zerocol = None
    spec = None
    for s in mods:
        if s in _SCALINGS:
            scaling = s
        elif s == "dominant":
            dominant = True
        elif s.startswith("zerocol"):
            v = s[len("zerocol"):]
            zerocol = (int(round(float(v) * (n - 1)))
                       if "." in v else int(v))
        elif s in _SPECTRA:
            if base not in _SPECTRAL_BASES:
                raise SlateError(
                    f"kind '{base}' takes no spectrum suffix '_{s}'")
            spec = s
        else:
            raise SlateError(f"unknown suffix '_{s}' in kind '{kind}'")

    a = _generate_base(base, spec, m, n, k, dtype, seed, cond, sigma)

    if dominant:
        if base in ("rand", "rands", "randn", "randb", "randr"):
            # the established rand_dominant contract: + min(m,n)·I
            a = a + k * jnp.eye(m, n, dtype=dtype)
        else:
            # reference: dominant only implemented for rand kinds; we
            # extend it (sum of |row| added to the diagonal)
            rs = jnp.sum(jnp.abs(a), axis=1)
            idx = jnp.arange(k)
            a = a.at[idx, idx].add(rs[:k].astype(a.dtype))
    if scaling is not None:
        real = jnp.finfo(dtype).dtype
        fi = jnp.finfo(real)
        target = {"ufl": float(fi.tiny), "ofl": float(fi.max),
                  "small": float(np.sqrt(fi.tiny)),
                  "large": float(np.sqrt(fi.max))}[scaling]
        amax = jnp.max(jnp.abs(a))
        a = a * jnp.where(amax == 0, 1.0,
                          jnp.asarray(target, real) / amax).astype(dtype)
    if zerocol is not None:
        if not 0 <= zerocol < n:
            raise SlateError(f"zerocol {zerocol} outside [0, {n})")
        a = a.at[:, zerocol].set(0)
        if base in ("heev", "syev", "poev", "spd") and zerocol < m:
            a = a.at[zerocol, :].set(0)
    return a


def _generate_base(base, spec, m, n, k, dtype, seed, cond, sigma):
    mx = max(m, n)
    E = _entrywise

    if base in ("zeros", "zero"):
        return jnp.zeros((m, n), dtype)
    if base in ("ones", "one"):
        return jnp.ones((m, n), dtype)
    if base == "identity":
        return jnp.eye(m, n, dtype=dtype)
    if base == "ij":
        s = 1.0 / 10 ** np.ceil(np.log10(max(n, 2)))
        return E(m, n, dtype, lambda i, j: i + j * s)
    if base == "jordan":
        return jnp.eye(m, n, dtype=dtype) + jnp.eye(m, n, k=1, dtype=dtype)
    if base == "jordanT":
        return jnp.eye(m, n, dtype=dtype) + jnp.eye(m, n, k=-1, dtype=dtype)
    if base == "chebspec":
        # nonsingular Chebyshev spectral differentiation matrix
        # (generate_matrix_ge.cc:129-151)
        pi = np.pi

        def cheb(i, j):
            x_i = jnp.cos(pi * (i + 1) / mx)
            x_j = jnp.cos(pi * (j + 1) / mx)
            c_i = jnp.where(i == mx - 1, 2.0, 1.0)
            c_j = jnp.where(j == mx - 1, 2.0, 1.0)
            sgn = jnp.where((i + j) % 2 == 0, 1.0, -1.0)
            off = sgn * c_i / (c_j * jnp.where(i == j, 1.0, x_j - x_i))
            diag_last = (2.0 * mx * mx + 1) / -6.0
            diag = jnp.where(j + 1 == mx, diag_last,
                             -0.5 * x_i / (1.0 - x_i * x_i))
            return jnp.where(i == j, diag, off)

        return E(m, n, dtype, cheb)
    if base == "circul":
        return E(m, n, dtype,
                 lambda i, j: (j - i) % mx + 1)
    if base == "fiedler":
        return E(m, n, dtype, lambda i, j: jnp.abs(j - i))
    if base == "gfpp":
        return E(m, n, dtype, lambda i, j: jnp.where(
            j == n - 1, 1.0, jnp.where(i > j, -1.0,
                                       jnp.where(i == j, 0.5, 0.0))))
    if base == "kms":
        return E(m, n, dtype, lambda i, j: 0.5 ** jnp.abs(j - i))
    if base == "orthog":
        oc = np.sqrt(2.0 / (mx + 1))
        ic = np.pi / (mx + 1)
        return E(m, n, dtype,
                 lambda i, j: oc * jnp.sin((i + 1.0) * (j + 1.0) * ic))
    if base == "riemann":
        # matches the reference's own formula (generate_matrix_ge.cc:
        # riemann_entry: B_j % B_i == 0 → B_j − 1), which transposes the
        # classic Higham gallery definition; parity with the reference
        # wins here
        return E(m, n, dtype, lambda i, j: jnp.where(
            (j + 2) % (i + 2) == 0, (j + 2) - 1, -1))
    if base == "ris":
        return E(m, n, dtype, lambda i, j: 0.5 / (mx - j - i - 0.5))
    if base == "zielkeNS":
        return E(m, n, dtype, lambda i, j: jnp.where(
            j < i, 1.0, jnp.where((j + 1 == mx) & (i == 0), -1.0, 0.0)))
    if base == "minij":
        return E(m, n, dtype, lambda i, j: jnp.minimum(i, j) + 1)
    if base == "hilb":
        return E(m, n, dtype, lambda i, j: 1.0 / (i + j + 1))
    if base == "frank":
        return E(m, n, dtype, lambda i, j: jnp.where(
            i - j > 1, 0, jnp.where(i - j == 1, mx - j - 1, mx - j)))
    if base == "lehmer":
        return E(m, n, dtype, lambda i, j: (jnp.minimum(i, j) + 1.0)
                 / (jnp.maximum(i, j) + 1.0))
    if base == "lotkin":
        return E(m, n, dtype, lambda i, j: jnp.where(
            i == 0, 1.0, 1.0 / (i + j + 1)))
    if base == "redheff":
        return E(m, n, dtype, lambda i, j: jnp.where(
            ((j + 1) % (i + 1) == 0) | (j == 0), 1, 0))
    if base == "triw":
        return E(m, n, dtype, lambda i, j: jnp.where(
            i == j, 1, jnp.where(i > j, 0, -1)))
    if base == "pei":
        return E(m, n, dtype, lambda i, j: jnp.where(i == j, 2, 1))
    if base == "tridiag":
        return E(m, n, dtype, lambda i, j: jnp.where(
            i == j, 2, jnp.where(jnp.abs(i - j) == 1, -1, 0)))
    if base == "toeppen":
        return E(m, n, dtype, lambda i, j: jnp.where(
            jnp.abs(j - i) == 1, (j - i) * 10.0,
            jnp.where(jnp.abs(i - j) == 2, 1.0, 0.0)))
    if base == "parter":
        return E(m, n, dtype, lambda i, j: 1.0 / (i - j + 0.5))
    if base == "moler":
        return E(m, n, dtype, lambda i, j: jnp.where(
            i == j, i + 1.0, jnp.minimum(i, j) - 1.0))
    if base == "cauchy":
        return E(m, n, dtype, lambda i, j: 1.0 / (i + j + 2))
    if base == "chow":
        return E(m, n, dtype, lambda i, j: jnp.where(i - j < -1, 0, 1))
    if base == "clement":
        return E(m, n, dtype, lambda i, j: jnp.where(
            i - j == 1, mx - j - 1.0, jnp.where(i - j == -1, j * 1.0, 0.0)))
    if base == "gcdmat":
        i = jnp.arange(1, m + 1)[:, None]
        j = jnp.arange(1, n + 1)[None, :]
        return jnp.gcd(i, j).astype(dtype)

    if base in ("rand", "rands", "randn", "randb", "randr"):
        gen = {"rand": rnd.uniform, "rands": rnd.uniform_signed,
               "randn": rnd.normal, "randb": rnd.binary,
               "randr": rnd.rademacher}[base]
        a = gen(seed, m, n, dtype)
        return a

    if base == "diag":
        sig = _spectrum(spec or "logrand", k, cond, dtype, seed, sigma)
        return jnp.zeros((m, n), dtype).at[jnp.arange(k), jnp.arange(k)].set(
            sig.astype(dtype))

    if base == "svd":
        sig = _spectrum(spec or "logrand", k, cond, dtype, seed, sigma)
        u = _random_orthogonal(seed, m, dtype)[:, :k]
        v = _random_orthogonal(seed + 1, n, dtype)[:, :k]
        return (u * sig[None, :].astype(dtype)) @ jnp.conj(v).T

    if base in ("heev", "syev"):
        sig = _spectrum(spec or "logrand", k, cond, dtype, seed, sigma)
        if (spec or "").startswith("r") and spec in (
                "rlogrand", "rarith", "rgeo", "rcluster0", "rcluster1"):
            # reference heev 'r' variants: random signs (rand_sign)
            sign = jnp.where(jax.random.bernoulli(
                jax.random.fold_in(jax.random.key(seed), 2), 0.5,
                (k,)), 1.0, -1.0).astype(sig.dtype)
            sig = sig * sign
        q = _random_orthogonal(seed, n, dtype)
        a = (q * sig[None, :].astype(dtype)) @ jnp.conj(q).T
        return 0.5 * (a + jnp.conj(a).T)

    if base in ("poev", "spd"):
        sig = jnp.abs(_spectrum(spec or "logrand", k, cond, dtype, seed,
                                sigma))
        q = _random_orthogonal(seed, n, dtype)
        a = (q * sig[None, :].astype(dtype)) @ jnp.conj(q).T
        return 0.5 * (a + jnp.conj(a).T)

    if base == "geev":
        # nonsymmetric with prescribed eigenvalues (reference
        # generate_type_geev.hh): A = V·Λ·V⁻¹ with a well-conditioned
        # nonorthogonal V = I + ½·strict_lower(G)/√n
        lam = _spectrum(spec or "logrand", n, cond, dtype, seed, sigma)
        g = rnd.normal(seed + 3, n, n, dtype)
        v = jnp.eye(n, dtype=dtype) + 0.5 * jnp.tril(g, -1) / jnp.sqrt(
            jnp.asarray(float(n), jnp.finfo(dtype).dtype)).astype(dtype)
        # A = V Λ V⁻¹  via  solve(Vᵀ, (V Λ)ᵀ)ᵀ
        vl = v * lam[None, :].astype(dtype)
        return jnp.linalg.solve(v.T, vl.T).T

    raise SlateError(f"unknown matrix kind '{base}'")


def cond_targeted(n: int, cond: float, dtype=jnp.float32, seed: int = 42,
                  spd: bool = False, spectrum: str = "geo") -> jax.Array:
    """Condition-targeted dense test operand (round 16): σ₁ = 1,
    σₙ = 1/cond with a latms-style geometric spectrum by default
    (LAPACK ``?latms`` MODE 3 / the reference's ``geo`` profile) —
    ``spd=True`` builds Q·Σ·Qᴴ (Hermitian positive definite, the
    pocondest/chol operand), ``spd=False`` builds U·Σ·Vᴴ (general, the
    gecondest/LU operand). κ₂ is ``cond`` BY CONSTRUCTION, which is
    what the numerics tests and the chaos suspect-demotion drill
    calibrate condest against; any profile from :func:`_spectrum`
    (arith, cluster0, logrand, ...) is accepted."""
    base = "spd" if spd else "svd"
    return generate_matrix(f"{base}_{spectrum}", n, dtype=dtype,
                           seed=seed, cond=float(cond))


def random_spd(m: int, nb_unused: int = 0, dtype=jnp.float32, seed: int = 0,
               ) -> jax.Array:
    """Well-conditioned SPD/HPD matrix: A = G·Gᴴ/m + I (the standard posv
    tester input; reference test/matrix_params)."""
    g = rnd.normal(seed, m, m, dtype)
    a = g @ jnp.conj(g).T / m + jnp.eye(m, dtype=dtype)
    return 0.5 * (a + jnp.conj(a).T)
