"""Deterministic, distribution-independent random matrix entries.

Reference: matgen/random.cc:43-72 — a counter-based Philox-2x64 RNG keyed
on (seed, global entry index) so generated matrices are identical under
any process distribution (CHANGELOG.md:77-79).

TPU-native equivalent: jax.random *is* a counter-based (threefry) RNG.
We generate at the *logical* (m, n) shape from key(seed) — never at the
padded/sharded shape — so the values depend only on (seed, m, n, kind),
not on tile size nb, process grid, or sharding. Padding and sharding are
applied after generation; under jit+GSPMD the generation itself is
partitioned across the mesh by XLA.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _key(seed: int) -> jax.Array:
    return jax.random.key(seed)


def uniform(seed: int, m: int, n: int, dtype, minval=0.0, maxval=1.0):
    """Entries ~ U[minval, maxval) ('rand' kind, matgen Dist::Uniform)."""
    if jnp.issubdtype(dtype, jnp.complexfloating):
        real_dtype = jnp.finfo(dtype).dtype
        k1, k2 = jax.random.split(_key(seed))
        re = jax.random.uniform(k1, (m, n), real_dtype, minval, maxval)
        im = jax.random.uniform(k2, (m, n), real_dtype, minval, maxval)
        return (re + 1j * im).astype(dtype)
    return jax.random.uniform(_key(seed), (m, n), dtype, minval, maxval)


def uniform_signed(seed: int, m: int, n: int, dtype):
    """'rands' kind: U[-1, 1)."""
    return uniform(seed, m, n, dtype, -1.0, 1.0)


def normal(seed: int, m: int, n: int, dtype):
    """'randn' kind: N(0, 1)."""
    if jnp.issubdtype(dtype, jnp.complexfloating):
        real_dtype = jnp.finfo(dtype).dtype
        k1, k2 = jax.random.split(_key(seed))
        re = jax.random.normal(k1, (m, n), real_dtype)
        im = jax.random.normal(k2, (m, n), real_dtype)
        return (re + 1j * im).astype(dtype)
    return jax.random.normal(_key(seed), (m, n), dtype)


def binary(seed: int, m: int, n: int, dtype):
    """'randb' kind: entries in {0, 1}."""
    bits = jax.random.bernoulli(_key(seed), 0.5, (m, n))
    return bits.astype(dtype)


def rademacher(seed: int, m: int, n: int, dtype):
    """'randr' kind: entries in {-1, +1} (reference Dist::UniformSigned
    rounded — matgen random.hh randr)."""
    bits = jax.random.bernoulli(_key(seed), 0.5, (m, n))
    return jnp.where(bits, 1.0, -1.0).astype(dtype)
