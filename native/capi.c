/* slate-tpu routine-level C API.
 *
 * Reference analog: the generated C API (tools/c_api/generate_*.py +
 * src/c_api/wrappers.cc) that exposes each driver as a C symbol.
 *
 * The TPU compute path lives in the Python/JAX runtime, so these
 * symbols embed the CPython interpreter (once, lazily) and dispatch to
 * slate_tpu.compat.lapack_api. Matrices are COLUMN-MAJOR double
 * buffers with leading dimension, LAPACK conventions; info is the
 * return value (0 = success, <0 = argument/runtime error).
 *
 * Build: native/Makefile target libslate_tpu_capi.so (links
 * libpython). C callers:
 *
 *     #include "slate_tpu_capi.h"
 *     info = slate_tpu_dgesv(n, nrhs, a, lda, ipiv, b, ldb);
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <stdint.h>
#include <string.h>

static int ensure_python(void) {
    if (!Py_IsInitialized()) {
        Py_InitializeEx(0);
        /* release the GIL acquired by initialization so other threads
         * can enter via PyGILState_Ensure (we never need the init
         * thread state again — every entry point brackets itself) */
        PyEval_SaveThread();
    }
    return Py_IsInitialized() ? 0 : -100;
}

/* Run a compat call: fn_name(args...) where buffers are passed through
 * memoryviews; results are copied back into the caller's buffers by
 * the Python helper (slate_tpu.compat.c_glue). */
static int call_glue(const char* fn, PyObject* args) {
    PyGILState_STATE g = PyGILState_Ensure();
    int rc = -101;
    PyObject *mod = NULL, *f = NULL, *res = NULL;
    mod = PyImport_ImportModule("slate_tpu.compat.c_glue");
    if (!mod) goto done;
    f = PyObject_GetAttrString(mod, fn);
    if (!f) goto done;
    res = PyObject_CallObject(f, args);
    if (!res) goto done;
    rc = (int)PyLong_AsLong(res);
done:
    if (PyErr_Occurred()) {
        PyErr_Print();
        if (rc >= 0) rc = -102;
    }
    Py_XDECREF(res);
    Py_XDECREF(f);
    Py_XDECREF(mod);
    PyGILState_Release(g);
    return rc;
}

static PyObject* mv(double* p, int64_t count) {
    return PyMemoryView_FromMemory((char*)p, count * (int64_t)sizeof(double),
                                   PyBUF_WRITE);
}

static PyObject* mvi(int64_t* p, int64_t count) {
    return PyMemoryView_FromMemory((char*)p, count * (int64_t)sizeof(int64_t),
                                   PyBUF_WRITE);
}

int64_t slate_tpu_dgesv(int64_t n, int64_t nrhs, double* a, int64_t lda,
                        int64_t* ipiv, double* b, int64_t ldb) {
    if (ensure_python()) return -100;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* args = Py_BuildValue(
        "(LLNLNNL)", (long long)n, (long long)nrhs, mv(a, lda * n),
        (long long)lda, mvi(ipiv, n), mv(b, ldb * nrhs), (long long)ldb);
    PyGILState_Release(g);
    if (!args) return -103;
    int rc = call_glue("c_dgesv", args);
    PyGILState_STATE g2 = PyGILState_Ensure();
    Py_DECREF(args);
    PyGILState_Release(g2);
    return rc;
}

int64_t slate_tpu_dpotrf(const char* uplo, int64_t n, double* a,
                         int64_t lda) {
    if (ensure_python()) return -100;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* args = Py_BuildValue("(sLNL)", uplo, (long long)n,
                                   mv(a, lda * n), (long long)lda);
    PyGILState_Release(g);
    if (!args) return -103;
    int rc = call_glue("c_dpotrf", args);
    PyGILState_STATE g2 = PyGILState_Ensure();
    Py_DECREF(args);
    PyGILState_Release(g2);
    return rc;
}

int64_t slate_tpu_dposv(const char* uplo, int64_t n, int64_t nrhs,
                        double* a, int64_t lda, double* b, int64_t ldb) {
    if (ensure_python()) return -100;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* args = Py_BuildValue(
        "(sLLNLNL)", uplo, (long long)n, (long long)nrhs, mv(a, lda * n),
        (long long)lda, mv(b, ldb * nrhs), (long long)ldb);
    PyGILState_Release(g);
    if (!args) return -103;
    int rc = call_glue("c_dposv", args);
    PyGILState_STATE g2 = PyGILState_Ensure();
    Py_DECREF(args);
    PyGILState_Release(g2);
    return rc;
}

int64_t slate_tpu_dgels(int64_t m, int64_t n, int64_t nrhs, double* a,
                        int64_t lda, double* b, int64_t ldb) {
    if (ensure_python()) return -100;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* args = Py_BuildValue(
        "(LLLNLNL)", (long long)m, (long long)n, (long long)nrhs,
        mv(a, lda * n), (long long)lda, mv(b, ldb * nrhs), (long long)ldb);
    PyGILState_Release(g);
    if (!args) return -103;
    int rc = call_glue("c_dgels", args);
    PyGILState_STATE g2 = PyGILState_Ensure();
    Py_DECREF(args);
    PyGILState_Release(g2);
    return rc;
}
