/* slate-tpu routine-level C API.
 *
 * Reference analog: the generated C API (tools/c_api/generate_*.py +
 * src/c_api/wrappers.cc) that exposes each driver as a C symbol.
 *
 * The TPU compute path lives in the Python/JAX runtime, so these
 * symbols embed the CPython interpreter (once, lazily) and dispatch to
 * slate_tpu.compat.lapack_api. Matrices are COLUMN-MAJOR double
 * buffers with leading dimension, LAPACK conventions; info is the
 * return value (0 = success, <0 = argument/runtime error).
 *
 * Build: native/Makefile target libslate_tpu_capi.so (links
 * libpython). C callers:
 *
 *     #include "slate_tpu_capi.h"
 *     info = slate_tpu_dgesv(n, nrhs, a, lda, ipiv, b, ldb);
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <stdint.h>
#include <string.h>

static int ensure_python(void) {
    if (!Py_IsInitialized()) {
        Py_InitializeEx(0);
        /* release the GIL acquired by initialization so other threads
         * can enter via PyGILState_Ensure (we never need the init
         * thread state again — every entry point brackets itself) */
        PyEval_SaveThread();
    }
    return Py_IsInitialized() ? 0 : -100;
}

/* Run a compat call: fn_name(args...) where buffers are passed through
 * memoryviews; results are copied back into the caller's buffers by
 * the Python helper (slate_tpu.compat.c_glue). */
static int call_glue(const char* fn, PyObject* args) {
    PyGILState_STATE g = PyGILState_Ensure();
    int rc = -101;
    PyObject *mod = NULL, *f = NULL, *res = NULL;
    mod = PyImport_ImportModule("slate_tpu.compat.c_glue");
    if (!mod) goto done;
    f = PyObject_GetAttrString(mod, fn);
    if (!f) goto done;
    res = PyObject_CallObject(f, args);
    if (!res) goto done;
    rc = (int)PyLong_AsLong(res);
done:
    if (PyErr_Occurred()) {
        PyErr_Print();
        if (rc >= 0) rc = -102;
    }
    Py_XDECREF(res);
    Py_XDECREF(f);
    Py_XDECREF(mod);
    PyGILState_Release(g);
    return rc;
}

static PyObject* mv(double* p, int64_t count) {
    return PyMemoryView_FromMemory((char*)p, count * (int64_t)sizeof(double),
                                   PyBUF_WRITE);
}

static PyObject* mvi(int64_t* p, int64_t count) {
    return PyMemoryView_FromMemory((char*)p, count * (int64_t)sizeof(int64_t),
                                   PyBUF_WRITE);
}

/* Build the args tuple from up to three pre-made memoryviews using the
 * "O" format (Py_BuildValue takes its own reference), then drop ours —
 * so a failure anywhere leaks nothing (each view is DECREFed exactly
 * once here whether or not the tuple was built). Any pending error is
 * printed while the GIL is still held. */
static PyObject* finish_args(PyGILState_STATE g, PyObject* args,
                             PyObject* v0, PyObject* v1, PyObject* v2) {
    Py_XDECREF(v0);
    Py_XDECREF(v1);
    Py_XDECREF(v2);
    if (!args && PyErr_Occurred()) PyErr_Print();
    PyGILState_Release(g);
    return args;
}

int64_t slate_tpu_dgesv(int64_t n, int64_t nrhs, double* a, int64_t lda,
                        int64_t* ipiv, double* b, int64_t ldb) {
    if (ensure_python()) return -100;
    PyGILState_STATE g = PyGILState_Ensure();
    /* short-circuit after a NULL: calling further C-API constructors
     * with an exception pending is undefined (asserts on debug builds) */
    PyObject* mva = mv(a, lda * n);
    PyObject* mvp = mva ? mvi(ipiv, n) : NULL;
    PyObject* mvb = mvp ? mv(b, ldb * nrhs) : NULL;
    PyObject* args = (mva && mvp && mvb)
        ? Py_BuildValue("(LLOLOOL)", (long long)n, (long long)nrhs, mva,
                        (long long)lda, mvp, mvb, (long long)ldb)
        : NULL;
    args = finish_args(g, args, mva, mvp, mvb);
    if (!args) return -103;
    int rc = call_glue("c_dgesv", args);
    PyGILState_STATE g2 = PyGILState_Ensure();
    Py_DECREF(args);
    PyGILState_Release(g2);
    return rc;
}

int64_t slate_tpu_dpotrf(const char* uplo, int64_t n, double* a,
                         int64_t lda) {
    if (ensure_python()) return -100;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* mva = mv(a, lda * n);
    PyObject* args = mva
        ? Py_BuildValue("(sLOL)", uplo, (long long)n, mva, (long long)lda)
        : NULL;
    args = finish_args(g, args, mva, NULL, NULL);
    if (!args) return -103;
    int rc = call_glue("c_dpotrf", args);
    PyGILState_STATE g2 = PyGILState_Ensure();
    Py_DECREF(args);
    PyGILState_Release(g2);
    return rc;
}

int64_t slate_tpu_dposv(const char* uplo, int64_t n, int64_t nrhs,
                        double* a, int64_t lda, double* b, int64_t ldb) {
    if (ensure_python()) return -100;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* mva = mv(a, lda * n);
    PyObject* mvb = mva ? mv(b, ldb * nrhs) : NULL;
    PyObject* args = (mva && mvb)
        ? Py_BuildValue("(sLLOLOL)", uplo, (long long)n, (long long)nrhs,
                        mva, (long long)lda, mvb, (long long)ldb)
        : NULL;
    args = finish_args(g, args, mva, mvb, NULL);
    if (!args) return -103;
    int rc = call_glue("c_dposv", args);
    PyGILState_STATE g2 = PyGILState_Ensure();
    Py_DECREF(args);
    PyGILState_Release(g2);
    return rc;
}

int64_t slate_tpu_dgels(int64_t m, int64_t n, int64_t nrhs, double* a,
                        int64_t lda, double* b, int64_t ldb) {
    if (ensure_python()) return -100;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* mva = mv(a, lda * n);
    PyObject* mvb = mva ? mv(b, ldb * nrhs) : NULL;
    PyObject* args = (mva && mvb)
        ? Py_BuildValue("(LLLOLOL)", (long long)m, (long long)n,
                        (long long)nrhs, mva, (long long)lda, mvb,
                        (long long)ldb)
        : NULL;
    args = finish_args(g, args, mva, mvb, NULL);
    if (!args) return -103;
    int rc = call_glue("c_dgels", args);
    PyGILState_STATE g2 = PyGILState_Ensure();
    Py_DECREF(args);
    PyGILState_Release(g2);
    return rc;
}
