/* slate-tpu routine-level C API: embedding helpers.
 *
 * Reference analog: the generated C API (tools/c_api/generate_*.py +
 * src/c_api/wrappers.cc) that exposes each driver as a C symbol.
 *
 * The TPU compute path lives in the Python/JAX runtime, so these
 * symbols embed the CPython interpreter (once, lazily) and dispatch to
 * slate_tpu.compat.c_glue. Matrices are COLUMN-MAJOR buffers with
 * leading dimension, LAPACK conventions; info is the return value
 * (0 = success, <0 = argument/runtime error).
 *
 * The routine entry points themselves (s/d/c/z × gesv...lange) are
 * GENERATED into capi_gen.c by tools/gen_capi.py — this file holds
 * only the shared embedding machinery.
 *
 * Build: native/Makefile target libslate_tpu_capi.so (links
 * libpython). C callers:
 *
 *     #include "slate_tpu_capi.h"
 *     info = slate_tpu_dgesv(n, nrhs, a, lda, ipiv, b, ldb);
 */

#include "capi_common.h"

#include <string.h>

int ensure_python(void) {
    if (!Py_IsInitialized()) {
        Py_InitializeEx(0);
        /* release the GIL acquired by initialization so other threads
         * can enter via PyGILState_Ensure (we never need the init
         * thread state again — every entry point brackets itself) */
        PyEval_SaveThread();
    }
    return Py_IsInitialized() ? 0 : -100;
}

PyObject* stc_mv(void* p, int64_t bytes) {
    if (!p) {
        Py_INCREF(Py_None);
        return Py_None;
    }
    return PyMemoryView_FromMemory((char*)p, bytes, PyBUF_WRITE);
}

PyObject* stc_finish(PyGILState_STATE g, PyObject* args, PyObject* v0,
                     PyObject* v1, PyObject* v2, PyObject* v3) {
    /* each view was given to Py_BuildValue with "O" (which takes its
     * own reference), so dropping ours here leaks nothing whether or
     * not the tuple was built */
    Py_XDECREF(v0);
    Py_XDECREF(v1);
    Py_XDECREF(v2);
    Py_XDECREF(v3);
    if (!args && PyErr_Occurred()) PyErr_Print();
    PyGILState_Release(g);
    return args;
}

int64_t stc_run(const char* fn, PyObject* args) {
    if (!args) return -103;
    PyGILState_STATE g = PyGILState_Ensure();
    int64_t rc = -101;
    PyObject *mod = NULL, *f = NULL, *res = NULL;
    mod = PyImport_ImportModule("slate_tpu.compat.c_glue");
    if (!mod) goto done;
    f = PyObject_GetAttrString(mod, fn);
    if (!f) goto done;
    res = PyObject_CallObject(f, args);
    if (!res) goto done;
    rc = (int64_t)PyLong_AsLongLong(res);
done:
    if (PyErr_Occurred()) {
        PyErr_Print();
        if (rc >= 0) rc = -102;
    }
    Py_XDECREF(res);
    Py_XDECREF(f);
    Py_XDECREF(mod);
    Py_DECREF(args);
    PyGILState_Release(g);
    return rc;
}
