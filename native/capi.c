/* slate-tpu routine-level C API.
 *
 * Reference analog: the generated C API (tools/c_api/generate_*.py +
 * src/c_api/wrappers.cc) that exposes each driver as a C symbol.
 *
 * The TPU compute path lives in the Python/JAX runtime, so these
 * symbols embed the CPython interpreter (once, lazily) and dispatch to
 * slate_tpu.compat.lapack_api. Matrices are COLUMN-MAJOR double
 * buffers with leading dimension, LAPACK conventions; info is the
 * return value (0 = success, <0 = argument/runtime error).
 *
 * Build: native/Makefile target libslate_tpu_capi.so (links
 * libpython). C callers:
 *
 *     #include "slate_tpu_capi.h"
 *     info = slate_tpu_dgesv(n, nrhs, a, lda, ipiv, b, ldb);
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <stdint.h>
#include <string.h>

static int ensure_python(void) {
    if (!Py_IsInitialized()) {
        Py_InitializeEx(0);
        /* release the GIL acquired by initialization so other threads
         * can enter via PyGILState_Ensure (we never need the init
         * thread state again — every entry point brackets itself) */
        PyEval_SaveThread();
    }
    return Py_IsInitialized() ? 0 : -100;
}

/* Run a compat call: fn_name(args...) where buffers are passed through
 * memoryviews; results are copied back into the caller's buffers by
 * the Python helper (slate_tpu.compat.c_glue). */
static int call_glue(const char* fn, PyObject* args) {
    PyGILState_STATE g = PyGILState_Ensure();
    int rc = -101;
    PyObject *mod = NULL, *f = NULL, *res = NULL;
    mod = PyImport_ImportModule("slate_tpu.compat.c_glue");
    if (!mod) goto done;
    f = PyObject_GetAttrString(mod, fn);
    if (!f) goto done;
    res = PyObject_CallObject(f, args);
    if (!res) goto done;
    rc = (int)PyLong_AsLong(res);
done:
    if (PyErr_Occurred()) {
        PyErr_Print();
        if (rc >= 0) rc = -102;
    }
    Py_XDECREF(res);
    Py_XDECREF(f);
    Py_XDECREF(mod);
    PyGILState_Release(g);
    return rc;
}

static PyObject* mv(double* p, int64_t count) {
    return PyMemoryView_FromMemory((char*)p, count * (int64_t)sizeof(double),
                                   PyBUF_WRITE);
}

static PyObject* mvi(int64_t* p, int64_t count) {
    return PyMemoryView_FromMemory((char*)p, count * (int64_t)sizeof(int64_t),
                                   PyBUF_WRITE);
}

/* Build the args tuple from up to four pre-made memoryviews using the
 * "O" format (Py_BuildValue takes its own reference), then drop ours —
 * so a failure anywhere leaks nothing (each view is DECREFed exactly
 * once here whether or not the tuple was built). Any pending error is
 * printed while the GIL is still held. */
static PyObject* finish_args4(PyGILState_STATE g, PyObject* args,
                              PyObject* v0, PyObject* v1, PyObject* v2,
                              PyObject* v3) {
    Py_XDECREF(v0);
    Py_XDECREF(v1);
    Py_XDECREF(v2);
    Py_XDECREF(v3);
    if (!args && PyErr_Occurred()) PyErr_Print();
    PyGILState_Release(g);
    return args;
}

static PyObject* finish_args(PyGILState_STATE g, PyObject* args,
                             PyObject* v0, PyObject* v1, PyObject* v2) {
    return finish_args4(g, args, v0, v1, v2, NULL);
}

/* Dispatch one pre-built args tuple to a c_glue function and clean up. */
static int64_t run_glue(const char* fn, PyObject* args) {
    if (!args) return -103;
    int rc = call_glue(fn, args);
    PyGILState_STATE g = PyGILState_Ensure();
    Py_DECREF(args);
    PyGILState_Release(g);
    return rc;
}

int64_t slate_tpu_dgesv(int64_t n, int64_t nrhs, double* a, int64_t lda,
                        int64_t* ipiv, double* b, int64_t ldb) {
    if (ensure_python()) return -100;
    PyGILState_STATE g = PyGILState_Ensure();
    /* short-circuit after a NULL: calling further C-API constructors
     * with an exception pending is undefined (asserts on debug builds) */
    PyObject* mva = mv(a, lda * n);
    PyObject* mvp = mva ? mvi(ipiv, n) : NULL;
    PyObject* mvb = mvp ? mv(b, ldb * nrhs) : NULL;
    PyObject* args = (mva && mvp && mvb)
        ? Py_BuildValue("(LLOLOOL)", (long long)n, (long long)nrhs, mva,
                        (long long)lda, mvp, mvb, (long long)ldb)
        : NULL;
    return run_glue("c_dgesv", finish_args(g, args, mva, mvp, mvb));
}

int64_t slate_tpu_dpotrf(const char* uplo, int64_t n, double* a,
                         int64_t lda) {
    if (ensure_python()) return -100;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* mva = mv(a, lda * n);
    PyObject* args = mva
        ? Py_BuildValue("(sLOL)", uplo, (long long)n, mva, (long long)lda)
        : NULL;
    return run_glue("c_dpotrf", finish_args(g, args, mva, NULL, NULL));
}

int64_t slate_tpu_dposv(const char* uplo, int64_t n, int64_t nrhs,
                        double* a, int64_t lda, double* b, int64_t ldb) {
    if (ensure_python()) return -100;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* mva = mv(a, lda * n);
    PyObject* mvb = mva ? mv(b, ldb * nrhs) : NULL;
    PyObject* args = (mva && mvb)
        ? Py_BuildValue("(sLLOLOL)", uplo, (long long)n, (long long)nrhs,
                        mva, (long long)lda, mvb, (long long)ldb)
        : NULL;
    return run_glue("c_dposv", finish_args(g, args, mva, mvb, NULL));
}

int64_t slate_tpu_dgels(int64_t m, int64_t n, int64_t nrhs, double* a,
                        int64_t lda, double* b, int64_t ldb) {
    if (ensure_python()) return -100;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* mva = mv(a, lda * n);
    PyObject* mvb = mva ? mv(b, ldb * nrhs) : NULL;
    PyObject* args = (mva && mvb)
        ? Py_BuildValue("(LLLOLOL)", (long long)m, (long long)n,
                        (long long)nrhs, mva, (long long)lda, mvb,
                        (long long)ldb)
        : NULL;
    return run_glue("c_dgels", finish_args(g, args, mva, mvb, NULL));
}

int64_t slate_tpu_dgetrf(int64_t m, int64_t n, double* a, int64_t lda,
                         int64_t* ipiv) {
    if (ensure_python()) return -100;
    PyGILState_STATE g = PyGILState_Ensure();
    int64_t k = m < n ? m : n;
    PyObject* mva = mv(a, lda * n);
    PyObject* mvp = mva ? mvi(ipiv, k) : NULL;
    PyObject* args = (mva && mvp)
        ? Py_BuildValue("(LLOLO)", (long long)m, (long long)n, mva,
                        (long long)lda, mvp)
        : NULL;
    return run_glue("c_dgetrf", finish_args(g, args, mva, mvp, NULL));
}

int64_t slate_tpu_dgetrs(const char* trans, int64_t n, int64_t nrhs,
                         double* a, int64_t lda, int64_t* ipiv, double* b,
                         int64_t ldb) {
    if (ensure_python()) return -100;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* mva = mv(a, lda * n);
    PyObject* mvp = mva ? mvi(ipiv, n) : NULL;
    PyObject* mvb = mvp ? mv(b, ldb * nrhs) : NULL;
    PyObject* args = (mva && mvp && mvb)
        ? Py_BuildValue("(sLLOLOOL)", trans, (long long)n, (long long)nrhs,
                        mva, (long long)lda, mvp, mvb, (long long)ldb)
        : NULL;
    return run_glue("c_dgetrs", finish_args(g, args, mva, mvp, mvb));
}

int64_t slate_tpu_dpotrs(const char* uplo, int64_t n, int64_t nrhs,
                         double* a, int64_t lda, double* b, int64_t ldb) {
    if (ensure_python()) return -100;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* mva = mv(a, lda * n);
    PyObject* mvb = mva ? mv(b, ldb * nrhs) : NULL;
    PyObject* args = (mva && mvb)
        ? Py_BuildValue("(sLLOLOL)", uplo, (long long)n, (long long)nrhs,
                        mva, (long long)lda, mvb, (long long)ldb)
        : NULL;
    return run_glue("c_dpotrs", finish_args(g, args, mva, mvb, NULL));
}

int64_t slate_tpu_dsyev(const char* jobz, const char* uplo, int64_t n,
                        double* a, int64_t lda, double* w) {
    if (ensure_python()) return -100;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* mva = mv(a, lda * n);
    PyObject* mvw = mva ? mv(w, n) : NULL;
    PyObject* args = (mva && mvw)
        ? Py_BuildValue("(ssLOLO)", jobz, uplo, (long long)n, mva,
                        (long long)lda, mvw)
        : NULL;
    return run_glue("c_dsyev", finish_args(g, args, mva, mvw, NULL));
}

int64_t slate_tpu_dgesvd(const char* jobu, const char* jobvt, int64_t m,
                         int64_t n, double* a, int64_t lda, double* s,
                         double* u, int64_t ldu, double* vt, int64_t ldvt) {
    if (ensure_python()) return -100;
    PyGILState_STATE g = PyGILState_Ensure();
    int64_t k = m < n ? m : n;
    /* thin ('S') and values-only ('N') jobs only: 'A' (full square U/VT)
     * and 'O' (overwrite A) are not provided by the thin-SVD driver —
     * reject them instead of writing a partial result */
    if (jobu && (jobu[0] == 'a' || jobu[0] == 'A'
                 || jobu[0] == 'o' || jobu[0] == 'O')) return -1;
    if (jobvt && (jobvt[0] == 'a' || jobvt[0] == 'A'
                  || jobvt[0] == 'o' || jobvt[0] == 'O')) return -2;
    int want_u = jobu && (jobu[0] == 's' || jobu[0] == 'S');
    int want_v = jobvt && (jobvt[0] == 's' || jobvt[0] == 'S');
    PyObject* mva = mv(a, lda * n);
    PyObject* mvs = mva ? mv(s, k) : NULL;
    PyObject* mvu = NULL;
    PyObject* mvv = NULL;
    PyObject* args = NULL;
    if (mvs) {
        mvu = want_u ? mv(u, ldu * k) : (Py_INCREF(Py_None), Py_None);
        mvv = mvu && want_v ? mv(vt, ldvt * n)
                            : (mvu ? (Py_INCREF(Py_None), Py_None) : NULL);
    }
    if (mva && mvs && mvu && mvv)
        args = Py_BuildValue("(ssLLOLOOLOL)", jobu, jobvt, (long long)m,
                             (long long)n, mva, (long long)lda, mvs, mvu,
                             (long long)ldu, mvv, (long long)ldvt);
    return run_glue("c_dgesvd", finish_args4(g, args, mva, mvs, mvu, mvv));
}

int64_t slate_tpu_dgemm(const char* transa, const char* transb, int64_t m,
                        int64_t n, int64_t k, double alpha, double* a,
                        int64_t lda, double* b, int64_t ldb, double beta,
                        double* c, int64_t ldc) {
    if (ensure_python()) return -100;
    PyGILState_STATE g = PyGILState_Ensure();
    int64_t cols_a = (transa[0] == 'n' || transa[0] == 'N') ? k : m;
    int64_t cols_b = (transb[0] == 'n' || transb[0] == 'N') ? n : k;
    PyObject* mva = mv(a, lda * cols_a);
    PyObject* mvb = mva ? mv(b, ldb * cols_b) : NULL;
    PyObject* mvc = mvb ? mv(c, ldc * n) : NULL;
    PyObject* args = (mva && mvb && mvc)
        ? Py_BuildValue("(ssLLLdOLOLdOL)", transa, transb, (long long)m,
                        (long long)n, (long long)k, alpha, mva,
                        (long long)lda, mvb, (long long)ldb, beta, mvc,
                        (long long)ldc)
        : NULL;
    return run_glue("c_dgemm", finish_args(g, args, mva, mvb, mvc));
}

int64_t slate_tpu_dtrsm(const char* side, const char* uplo,
                        const char* transa, const char* diag, int64_t m,
                        int64_t n, double alpha, double* a, int64_t lda,
                        double* b, int64_t ldb) {
    if (ensure_python()) return -100;
    PyGILState_STATE g = PyGILState_Ensure();
    int64_t ka = (side[0] == 'l' || side[0] == 'L') ? m : n;
    PyObject* mva = mv(a, lda * ka);
    PyObject* mvb = mva ? mv(b, ldb * n) : NULL;
    PyObject* args = (mva && mvb)
        ? Py_BuildValue("(ssssLLdOLOL)", side, uplo, transa, diag,
                        (long long)m, (long long)n, alpha, mva,
                        (long long)lda, mvb, (long long)ldb)
        : NULL;
    return run_glue("c_dtrsm", finish_args(g, args, mva, mvb, NULL));
}

double slate_tpu_dlange(const char* norm, int64_t m, int64_t n, double* a,
                        int64_t lda) {
    if (ensure_python()) return -1.0;
    PyGILState_STATE g = PyGILState_Ensure();
    double out = -1.0;
    PyObject* mva = mv(a, lda * n);
    PyObject* mvo = mva ? mv(&out, 1) : NULL;
    PyObject* args = (mva && mvo)
        ? Py_BuildValue("(sLLOLO)", norm, (long long)m, (long long)n, mva,
                        (long long)lda, mvo)
        : NULL;
    int64_t rc = run_glue("c_dlange", finish_args(g, args, mva, mvo, NULL));
    return rc == 0 ? out : -1.0;
}
