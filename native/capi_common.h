/* Shared embedding helpers for the slate-tpu C API (implemented in
 * capi.c, used by the generated capi_gen.c). */
#ifndef SLATE_TPU_CAPI_COMMON_H
#define SLATE_TPU_CAPI_COMMON_H

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* Lazily initialize the embedded interpreter; 0 on success. */
int ensure_python(void);

/* Writable memoryview over caller memory; NULL pointer maps to
 * Py_None (optional buffers, e.g. gesvd with jobu='n'). */
PyObject* stc_mv(void* p, int64_t bytes);

/* Drop up to four view references, print pending errors, release the
 * GIL, and pass the args tuple through (possibly NULL). */
PyObject* stc_finish(PyGILState_STATE g, PyObject* args, PyObject* v0,
                     PyObject* v1, PyObject* v2, PyObject* v3);

/* Call slate_tpu.compat.c_glue.<fn>(*args) and return its int result
 * (negative on embedding/Python failure). Consumes args. */
int64_t stc_run(const char* fn, PyObject* args);

#ifdef __cplusplus
}
#endif
#endif
