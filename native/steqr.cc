// slate-tpu native host runtime: implicit-shift QR iteration (steqr).
//
// TPU-native analog of the reference's distributed steqr
// (src/steqr_impl.cc): there, every rank redundantly computes the
// Givens rotations of each sweep and applies them to its own rows of a
// 1D-distributed Z with lapack::lasr (steqr_impl.cc:253-262, 389-398).
// Here the tridiagonal recurrence runs once on the host (it is a
// scalar chain no accelerator can parallelize) and the O(n) rotations
// per sweep are journaled, then applied to Z row-blocks in parallel by
// OpenMP threads — the same "redundant rotations, partitioned Z"
// design with threads standing in for ranks. Z is row-major, so one
// rotation touches adjacent elements z[r][i], z[r][i+1]: the inner
// loop streams each row once per sweep, cache-resident.
//
// The Python fallback (slate_tpu/linalg/eig.py::_steqr_py) implements
// the identical recurrence; this version lifts the per-rotation Python
// overhead (~µs each) to ~ns, raising the practical n from ~1k to ~8k.

#include <cstdint>
#include <cmath>
#include <limits>

#if defined(_OPENMP)
#include <omp.h>
#endif

namespace {

// Analytic eigendecomposition of the symmetric 2x2 [[a, b], [b, c]]
// (LAPACK dlaev2's formulas): rt1/rt2 the eigenvalues (|rt1| >= |rt2|),
// (cs1, sn1) the unit eigenvector of rt1. Closing 2x2 blocks with one
// exact rotation instead of iterating matches the reference's steqr
// (src/steqr_impl.cc calls lapack::laev2 for trailing 2x2 blocks).
void laev2(double a, double b, double c, double& rt1, double& rt2,
           double& cs1, double& sn1) {
    const double sm = a + c, df = a - c;
    const double adf = std::fabs(df), tb = b + b;
    const double ab = std::fabs(tb);
    double acmx, acmn;
    if (std::fabs(a) > std::fabs(c)) { acmx = a; acmn = c; }
    else                             { acmx = c; acmn = a; }
    double rt;
    if (adf > ab)      rt = adf * std::sqrt(1.0 + (ab / adf) * (ab / adf));
    else if (adf < ab) rt = ab * std::sqrt(1.0 + (adf / ab) * (adf / ab));
    else               rt = ab * std::sqrt(2.0);
    int sgn1;
    if (sm < 0.0) {
        rt1 = 0.5 * (sm - rt); sgn1 = -1;
        rt2 = (acmx / rt1) * acmn - (b / rt1) * b;
    } else if (sm > 0.0) {
        rt1 = 0.5 * (sm + rt); sgn1 = 1;
        rt2 = (acmx / rt1) * acmn - (b / rt1) * b;
    } else {
        rt1 = 0.5 * rt; rt2 = -0.5 * rt; sgn1 = 1;
    }
    double cs;
    int sgn2;
    if (df >= 0.0) { cs = df + rt; sgn2 = 1; }
    else           { cs = df - rt; sgn2 = -1; }
    const double acs = std::fabs(cs);
    if (acs > ab) {
        const double ct = -tb / cs;
        sn1 = 1.0 / std::sqrt(1.0 + ct * ct);
        cs1 = ct * sn1;
    } else if (ab == 0.0) {
        cs1 = 1.0; sn1 = 0.0;
    } else {
        const double tn = -cs / tb;
        cs1 = 1.0 / std::sqrt(1.0 + tn * tn);
        sn1 = tn * cs1;
    }
    if (sgn1 == sgn2) {
        const double tn = cs1;
        cs1 = -sn1;
        sn1 = tn;
    }
}

}  // namespace

extern "C" {

// In-place QR iteration on the symmetric tridiagonal (d[n], e[n-1]).
// If compute_z != 0, z is a row-major (n x n) matrix (typically I) to
// which all rotations are applied on the right (columns i, i+1).
// Returns 0 on convergence, >0 = LAPACK-style failure (unconverged),
// values unsorted (caller sorts).
int64_t st_steqr(int64_t n, double* d, double* e, double* z,
                 int64_t compute_z, int64_t max_iters) {
    if (n <= 1) return 0;
    double* cj = new double[n];
    double* sj = new double[n];

    // reference deflation criterion (src/steqr_impl.cc:238-241 —
    // LAPACK dsteqr's geometric mean): |e_i| <= eps sqrt(|d_i||d_{i+1}|)
    // + safe_min, evaluated in the UNSQUARED form sqrt(|d_i|)*sqrt(|d_{i+1}|)
    // so it cannot over/underflow at range extremes (LAPACK gets the
    // same robustness by dlascl-scaling each block to mid-range first;
    // the sqrt form needs no scaling pass). The geometric mean keeps
    // small couplings between same-magnitude SMALL diagonal entries
    // alive on graded spectra, where an additive tolerance
    // eps(|d_i|+|d_{i+1}|) would wrongly decouple them.
    const double eps = std::numeric_limits<double>::epsilon();
    const double safmin = std::numeric_limits<double>::min();

    int64_t iter = 0;
    for (; iter < max_iters; ++iter) {
        // deflate negligible off-diagonals
        for (int64_t i = 0; i < n - 1; ++i) {
            if (e[i] == 0.0) continue;  // already deflated: skip sqrts
            const double tol = eps * std::sqrt(std::fabs(d[i])) *
                               std::sqrt(std::fabs(d[i + 1])) + safmin;
            if (std::fabs(e[i]) <= tol) e[i] = 0.0;
        }
        // trailing undeflated block [lo, hi]
        int64_t hi = n - 1;
        while (hi > 0 && e[hi - 1] == 0.0) --hi;
        if (hi == 0) { delete[] cj; delete[] sj; return 0; }
        int64_t lo = hi - 1;
        while (lo > 0 && e[lo - 1] != 0.0) --lo;

        if (hi - lo == 1) {
            // close the 2x2 block with one exact rotation (laev2)
            double rt1, rt2, c2, s2;
            laev2(d[lo], e[lo], d[hi], rt1, rt2, c2, s2);
            d[lo] = rt1; d[hi] = rt2; e[lo] = 0.0;
            if (compute_z) {
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
                for (int64_t r = 0; r < n; ++r) {
                    double* zr = z + r * n;
                    const double zi = zr[lo];
                    zr[lo] =  c2 * zi + s2 * zr[hi];
                    zr[hi] = -s2 * zi + c2 * zr[hi];
                }
            }
            continue;
        }

        // Wilkinson shift from the trailing 2x2
        const double a11 = d[hi - 1], a22 = d[hi], ab = e[hi - 1];
        const double delta = (a11 - a22) / 2.0;
        const double sgn = (delta > 0.0) ? 1.0
                           : (delta < 0.0 ? -1.0 : 1.0);
        const double denom = delta + sgn * std::hypot(delta, ab);
        const double mu = (denom != 0.0) ? a22 - (ab * ab) / denom
                                         : a22 - ab;

        // bulge-chasing sweep over [lo, hi], journaling rotations
        double f = d[lo] - mu, g = e[lo];
        for (int64_t i = lo; i < hi; ++i) {
            double c, s, r;
            if (g == 0.0)      { c = 1.0; s = 0.0; r = f; }
            else if (f == 0.0) { c = 0.0; s = 1.0; r = g; }
            else { r = std::hypot(f, g); c = f / r; s = g / r; }
            if (i > lo) e[i - 1] = r;
            const double m11 = d[i], m12 = e[i], m22 = d[i + 1];
            d[i]     = c * c * m11 + 2.0 * c * s * m12 + s * s * m22;
            d[i + 1] = s * s * m11 - 2.0 * c * s * m12 + c * c * m22;
            e[i] = (c * c - s * s) * m12 + c * s * (m22 - m11);
            if (i < hi - 1) {
                const double bulge = s * e[i + 1];
                e[i + 1] = c * e[i + 1];
                f = e[i]; g = bulge;
            }
            cj[i] = c; sj[i] = s;
        }

        if (compute_z) {
            // apply the sweep's rotations to every row of Z; rows are
            // independent — the reference's rank-partitioned lasr
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
            for (int64_t r = 0; r < n; ++r) {
                double* zr = z + r * n;
                for (int64_t i = lo; i < hi; ++i) {
                    const double c = cj[i], s = sj[i];
                    const double zi = zr[i];
                    zr[i]     =  c * zi + s * zr[i + 1];
                    zr[i + 1] = -s * zi + c * zr[i + 1];
                }
            }
        }
    }
    delete[] cj; delete[] sj;
    // unconverged: count remaining nonzero off-diagonals (info analog)
    int64_t left = 0;
    for (int64_t i = 0; i < n - 1; ++i) if (e[i] != 0.0) ++left;
    return left > 0 ? left : 0;
}

}  // extern "C"
