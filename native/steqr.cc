// slate-tpu native host runtime: implicit-shift QR iteration (steqr).
//
// TPU-native analog of the reference's distributed steqr
// (src/steqr_impl.cc): there, every rank redundantly computes the
// Givens rotations of each sweep and applies them to its own rows of a
// 1D-distributed Z with lapack::lasr (steqr_impl.cc:253-262, 389-398).
// Here the tridiagonal recurrence runs once on the host (it is a
// scalar chain no accelerator can parallelize) and the O(n) rotations
// per sweep are journaled, then applied to Z row-blocks in parallel by
// OpenMP threads — the same "redundant rotations, partitioned Z"
// design with threads standing in for ranks. Z is row-major, so one
// rotation touches adjacent elements z[r][i], z[r][i+1]: the inner
// loop streams each row once per sweep, cache-resident.
//
// The Python fallback (slate_tpu/linalg/eig.py::_steqr_py) implements
// the identical recurrence; this version lifts the per-rotation Python
// overhead (~µs each) to ~ns, raising the practical n from ~1k to ~8k.

#include <cstdint>
#include <cmath>

#if defined(_OPENMP)
#include <omp.h>
#endif

extern "C" {

// In-place QR iteration on the symmetric tridiagonal (d[n], e[n-1]).
// If compute_z != 0, z is a row-major (n x n) matrix (typically I) to
// which all rotations are applied on the right (columns i, i+1).
// Returns 0 on convergence, >0 = LAPACK-style failure (unconverged),
// values unsorted (caller sorts).
int64_t st_steqr(int64_t n, double* d, double* e, double* z,
                 int64_t compute_z, int64_t max_iters) {
    if (n <= 1) return 0;
    double* cj = new double[n];
    double* sj = new double[n];

    int64_t iter = 0;
    for (; iter < max_iters; ++iter) {
        // deflate negligible off-diagonals
        for (int64_t i = 0; i < n - 1; ++i) {
            const double tol = 1e-16 * (std::fabs(d[i]) +
                                        std::fabs(d[i + 1]));
            if (std::fabs(e[i]) <= tol) e[i] = 0.0;
        }
        // trailing undeflated block [lo, hi]
        int64_t hi = n - 1;
        while (hi > 0 && e[hi - 1] == 0.0) --hi;
        if (hi == 0) { delete[] cj; delete[] sj; return 0; }
        int64_t lo = hi - 1;
        while (lo > 0 && e[lo - 1] != 0.0) --lo;

        // Wilkinson shift from the trailing 2x2
        const double a11 = d[hi - 1], a22 = d[hi], ab = e[hi - 1];
        const double delta = (a11 - a22) / 2.0;
        const double sgn = (delta > 0.0) ? 1.0
                           : (delta < 0.0 ? -1.0 : 1.0);
        const double denom = delta + sgn * std::hypot(delta, ab);
        const double mu = (denom != 0.0) ? a22 - (ab * ab) / denom
                                         : a22 - ab;

        // bulge-chasing sweep over [lo, hi], journaling rotations
        double f = d[lo] - mu, g = e[lo];
        for (int64_t i = lo; i < hi; ++i) {
            double c, s, r;
            if (g == 0.0)      { c = 1.0; s = 0.0; r = f; }
            else if (f == 0.0) { c = 0.0; s = 1.0; r = g; }
            else { r = std::hypot(f, g); c = f / r; s = g / r; }
            if (i > lo) e[i - 1] = r;
            const double m11 = d[i], m12 = e[i], m22 = d[i + 1];
            d[i]     = c * c * m11 + 2.0 * c * s * m12 + s * s * m22;
            d[i + 1] = s * s * m11 - 2.0 * c * s * m12 + c * c * m22;
            e[i] = (c * c - s * s) * m12 + c * s * (m22 - m11);
            if (i < hi - 1) {
                const double bulge = s * e[i + 1];
                e[i + 1] = c * e[i + 1];
                f = e[i]; g = bulge;
            }
            cj[i] = c; sj[i] = s;
        }

        if (compute_z) {
            // apply the sweep's rotations to every row of Z; rows are
            // independent — the reference's rank-partitioned lasr
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
            for (int64_t r = 0; r < n; ++r) {
                double* zr = z + r * n;
                for (int64_t i = lo; i < hi; ++i) {
                    const double c = cj[i], s = sj[i];
                    const double zi = zr[i];
                    zr[i]     =  c * zi + s * zr[i + 1];
                    zr[i + 1] = -s * zi + c * zr[i + 1];
                }
            }
        }
    }
    delete[] cj; delete[] sj;
    // unconverged: count remaining nonzero off-diagonals (info analog)
    int64_t left = 0;
    for (int64_t i = 0; i < n - 1; ++i) if (e[i] != 0.0) ++left;
    return left > 0 ? left : 0;
}

}  // extern "C"
