// slate-tpu native host runtime: layout conversion kernels.
//
// TPU-native analog of the reference's data-interchange machinery:
//  - Matrix::fromScaLAPACK zero-copy wrapping of 2D block-cyclic buffers
//    (reference include/slate/Matrix.hh:73 and the scalapack_api/ layer,
//    e.g. scalapack_api/scalapack_potrf.cc:94-110 reading BLACS grids);
//  - the tile layout conversions (BaseMatrix.hh:551-603 col<->row major,
//    src/cuda/device_transpose.cu batched tile transpose).
//
// On TPU the device side needs none of this (XLA owns device layout), but
// the HOST side does: users arriving from ScaLAPACK hold per-process 2D
// block-cyclic local arrays, and staging those into the global row-major
// buffers jax.device_put expects is a memory-bound strided copy that
// belongs in native code. These kernels are exposed through ctypes
// (slate_tpu/interop/native.py) and parallelized with OpenMP, matching
// the reference's use of OpenMP for host-side data motion.
//
// All kernels are templated over the element TYPE and exported with an
// explicit element-size argument (4 = f32, 8 = f64, 16 = c128; c64 rides
// the f64 instantiation — any 8-byte POD moves identically), the same
// four-precision surface the reference's scalapack_api exports per
// routine (scalapack_api/scalapack_potrf.cc:44-110). The esize-less f64
// symbols are kept as wrappers for existing callers.
//
// Layout conventions:
//  - global: row-major (m x n), leading dimension ldg >= n.
//  - block-cyclic local: TRUE ScaLAPACK layout. The (p, q) process at
//    coords (pi, qi) owns tiles (i, j) with i % p == pi, j % q == qi
//    (block-cyclic with source process 0, the BLACS default); its local
//    buffer is a COLUMN-MAJOR (lld x nloc) array with lld >= mloc =
//    numroc(m, nb, pi, p), exactly what Cpdgemr2d / pdpotrf_ expect and
//    what the reference wraps zero-copy in Matrix::fromScaLAPACK
//    (include/slate/Matrix.hh:347). Local row li maps to global row
//    (li/nb * p + pi) * nb + li%nb; ragged final blocks are NOT padded
//    (matching numroc), so buffers from real ScaLAPACK/BLACS programs
//    are byte-compatible.

#include <cstdint>
#include <cstring>
#include <algorithm>

#if defined(_OPENMP)
#include <omp.h>
#endif

namespace {

struct alignas(16) c128 { double re, im; };
static_assert(sizeof(c128) == 16, "c128 must be 16 bytes");

// Number of local tile-rows for grid coordinate pi of p over mt tiles.
inline int64_t local_tiles(int64_t mt, int64_t p, int64_t pi) {
    return (mt - pi + p - 1) / p;
}

inline int64_t numroc_impl(int64_t m, int64_t nb, int64_t pi, int64_t p) {
    const int64_t nblocks = m / nb;
    int64_t loc = (nblocks / p) * nb;
    const int64_t extra = nblocks % p;
    if (pi < extra) loc += nb;
    else if (pi == extra) loc += m % nb;
    return loc;
}

template <typename T>
int64_t bc_pack_t(const T* global, int64_t m, int64_t n, int64_t ldg,
                  int64_t nb, int64_t p, int64_t q, int64_t pi, int64_t qi,
                  T* local, int64_t lld) {
    if (!global || !local || nb <= 0 || p <= 0 || q <= 0) return -1;
    if (pi < 0 || pi >= p || qi < 0 || qi >= q) return -2;
    if (lld < numroc_impl(m, nb, pi, p)) return -3;
    const int64_t mt = (m + nb - 1) / nb;
    const int64_t nt = (n + nb - 1) / nb;
    const int64_t mtl = local_tiles(mt, p, pi);
    const int64_t ntl = local_tiles(nt, q, qi);
#pragma omp parallel for collapse(2) schedule(static)
    for (int64_t jl = 0; jl < ntl; ++jl) {
        for (int64_t il = 0; il < mtl; ++il) {
            const int64_t gi = pi + il * p;   // global tile row
            const int64_t gj = qi + jl * q;   // global tile col
            const int64_t r0 = gi * nb, c0 = gj * nb;
            const int64_t rows = std::min(nb, m - r0);
            const int64_t cols = std::min(nb, n - c0);
            for (int64_t c = 0; c < cols; ++c) {
                T* dst = local + (jl * nb + c) * lld + il * nb;
                const T* src = global + r0 * ldg + (c0 + c);
                for (int64_t r = 0; r < rows; ++r)
                    dst[r] = src[r * ldg];
            }
        }
    }
    return 0;
}

template <typename T>
int64_t bc_unpack_t(const T* local, int64_t m, int64_t n, int64_t ldg,
                    int64_t nb, int64_t p, int64_t q, int64_t pi,
                    int64_t qi, T* global, int64_t lld) {
    if (!global || !local || nb <= 0 || p <= 0 || q <= 0) return -1;
    if (pi < 0 || pi >= p || qi < 0 || qi >= q) return -2;
    if (lld < numroc_impl(m, nb, pi, p)) return -3;
    const int64_t mt = (m + nb - 1) / nb;
    const int64_t nt = (n + nb - 1) / nb;
    const int64_t mtl = local_tiles(mt, p, pi);
    const int64_t ntl = local_tiles(nt, q, qi);
#pragma omp parallel for collapse(2) schedule(static)
    for (int64_t jl = 0; jl < ntl; ++jl) {
        for (int64_t il = 0; il < mtl; ++il) {
            const int64_t gi = pi + il * p;
            const int64_t gj = qi + jl * q;
            const int64_t r0 = gi * nb, c0 = gj * nb;
            const int64_t rows = std::min(nb, m - r0);
            const int64_t cols = std::min(nb, n - c0);
            for (int64_t c = 0; c < cols; ++c) {
                const T* src = local + (jl * nb + c) * lld + il * nb;
                T* dst = global + r0 * ldg + (c0 + c);
                for (int64_t r = 0; r < rows; ++r)
                    dst[r * ldg] = src[r];
            }
        }
    }
    return 0;
}

template <typename T>
int64_t tile_pack_t(const T* global, int64_t m, int64_t n, int64_t ldg,
                    int64_t nb, T* tiles) {
    if (!global || !tiles || nb <= 0) return -1;
    const int64_t mt = (m + nb - 1) / nb;
    const int64_t nt = (n + nb - 1) / nb;
#pragma omp parallel for collapse(2) schedule(static)
    for (int64_t i = 0; i < mt; ++i) {
        for (int64_t j = 0; j < nt; ++j) {
            const int64_t r0 = i * nb, c0 = j * nb;
            const int64_t rows = std::min(nb, m - r0);
            const int64_t cols = std::min(nb, n - c0);
            T* t = tiles + ((i * nt) + j) * nb * nb;
            for (int64_t r = 0; r < rows; ++r) {
                std::memcpy(t + r * nb, global + (r0 + r) * ldg + c0,
                            size_t(cols) * sizeof(T));
                if (cols < nb)
                    std::memset(t + r * nb + cols, 0,
                                size_t(nb - cols) * sizeof(T));
            }
            for (int64_t r = rows; r < nb; ++r)
                std::memset(t + r * nb, 0, size_t(nb) * sizeof(T));
        }
    }
    return 0;
}

template <typename T>
int64_t tile_unpack_t(const T* tiles, int64_t m, int64_t n, int64_t ldg,
                      int64_t nb, T* global) {
    if (!global || !tiles || nb <= 0) return -1;
    const int64_t mt = (m + nb - 1) / nb;
    const int64_t nt = (n + nb - 1) / nb;
#pragma omp parallel for collapse(2) schedule(static)
    for (int64_t i = 0; i < mt; ++i) {
        for (int64_t j = 0; j < nt; ++j) {
            const int64_t r0 = i * nb, c0 = j * nb;
            const int64_t rows = std::min(nb, m - r0);
            const int64_t cols = std::min(nb, n - c0);
            const T* t = tiles + ((i * nt) + j) * nb * nb;
            for (int64_t r = 0; r < rows; ++r)
                std::memcpy(global + (r0 + r) * ldg + c0, t + r * nb,
                            size_t(cols) * sizeof(T));
        }
    }
    return 0;
}

// Column-major (LAPACK/ScaLAPACK) <-> row-major conversion with OpenMP
// blocking (the host analog of device_transpose.cu).
template <typename T>
int64_t cm_to_rm_t(const T* cm, int64_t m, int64_t n, int64_t ldcm, T* rm,
                   int64_t ldrm) {
    if (!cm || !rm) return -1;
    const int64_t B = 64;
#pragma omp parallel for collapse(2) schedule(static)
    for (int64_t ib = 0; ib < m; ib += B) {
        for (int64_t jb = 0; jb < n; jb += B) {
            const int64_t ie = std::min(ib + B, m);
            const int64_t je = std::min(jb + B, n);
            for (int64_t j = jb; j < je; ++j)
                for (int64_t i = ib; i < ie; ++i)
                    rm[i * ldrm + j] = cm[j * ldcm + i];
        }
    }
    return 0;
}

template <typename T>
int64_t rm_to_cm_t(const T* rm, int64_t m, int64_t n, int64_t ldrm, T* cm,
                   int64_t ldcm) {
    if (!rm || !cm) return -1;
    const int64_t B = 64;
#pragma omp parallel for collapse(2) schedule(static)
    for (int64_t ib = 0; ib < m; ib += B) {
        for (int64_t jb = 0; jb < n; jb += B) {
            const int64_t ie = std::min(ib + B, m);
            const int64_t je = std::min(jb + B, n);
            for (int64_t i = ib; i < ie; ++i)
                for (int64_t j = jb; j < je; ++j)
                    cm[j * ldcm + i] = rm[i * ldrm + j];
        }
    }
    return 0;
}

}  // namespace

extern "C" {

int64_t st_numroc(int64_t m, int64_t nb, int64_t pi, int64_t p) {
    return numroc_impl(m, nb, pi, p);
}

// ---- element-size generic entry points (4 = f32, 8 = f64/c64, 16 = c128)

int64_t st_bc_pack_e(const void* global, int64_t m, int64_t n, int64_t ldg,
                     int64_t nb, int64_t p, int64_t q, int64_t pi,
                     int64_t qi, void* local, int64_t lld, int64_t esize) {
    return esize == 8
        ? bc_pack_t(static_cast<const double*>(global), m, n, ldg, nb,
                    p, q, pi, qi, static_cast<double*>(local), lld)
        : esize == 4
        ? bc_pack_t(static_cast<const float*>(global), m, n, ldg, nb,
                    p, q, pi, qi, static_cast<float*>(local), lld)
        : esize == 16
        ? bc_pack_t(static_cast<const c128*>(global), m, n, ldg, nb,
                    p, q, pi, qi, static_cast<c128*>(local), lld)
        : int64_t(-4);
}

int64_t st_bc_unpack_e(const void* local, int64_t m, int64_t n,
                       int64_t ldg, int64_t nb, int64_t p, int64_t q,
                       int64_t pi, int64_t qi, void* global, int64_t lld,
                       int64_t esize) {
    return esize == 8
        ? bc_unpack_t(static_cast<const double*>(local), m, n, ldg, nb, p,
                      q, pi, qi, static_cast<double*>(global), lld)
        : esize == 4
        ? bc_unpack_t(static_cast<const float*>(local), m, n, ldg, nb, p,
                      q, pi, qi, static_cast<float*>(global), lld)
        : esize == 16
        ? bc_unpack_t(static_cast<const c128*>(local), m, n, ldg, nb, p,
                      q, pi, qi, static_cast<c128*>(global), lld)
        : int64_t(-4);
}

int64_t st_tile_pack_e(const void* global, int64_t m, int64_t n,
                       int64_t ldg, int64_t nb, void* tiles,
                       int64_t esize) {
    return esize == 8
        ? tile_pack_t(static_cast<const double*>(global), m, n, ldg, nb,
                      static_cast<double*>(tiles))
        : esize == 4
        ? tile_pack_t(static_cast<const float*>(global), m, n, ldg, nb,
                      static_cast<float*>(tiles))
        : esize == 16
        ? tile_pack_t(static_cast<const c128*>(global), m, n, ldg, nb,
                      static_cast<c128*>(tiles))
        : int64_t(-4);
}

int64_t st_tile_unpack_e(const void* tiles, int64_t m, int64_t n,
                         int64_t ldg, int64_t nb, void* global,
                         int64_t esize) {
    return esize == 8
        ? tile_unpack_t(static_cast<const double*>(tiles), m, n, ldg, nb,
                        static_cast<double*>(global))
        : esize == 4
        ? tile_unpack_t(static_cast<const float*>(tiles), m, n, ldg, nb,
                        static_cast<float*>(global))
        : esize == 16
        ? tile_unpack_t(static_cast<const c128*>(tiles), m, n, ldg, nb,
                        static_cast<c128*>(global))
        : int64_t(-4);
}

int64_t st_colmajor_to_rowmajor_e(const void* cm, int64_t m, int64_t n,
                                  int64_t ldcm, void* rm, int64_t ldrm,
                                  int64_t esize) {
    return esize == 8
        ? cm_to_rm_t(static_cast<const double*>(cm), m, n, ldcm,
                     static_cast<double*>(rm), ldrm)
        : esize == 4
        ? cm_to_rm_t(static_cast<const float*>(cm), m, n, ldcm,
                     static_cast<float*>(rm), ldrm)
        : esize == 16
        ? cm_to_rm_t(static_cast<const c128*>(cm), m, n, ldcm,
                     static_cast<c128*>(rm), ldrm)
        : int64_t(-4);
}

int64_t st_rowmajor_to_colmajor_e(const void* rm, int64_t m, int64_t n,
                                  int64_t ldrm, void* cm, int64_t ldcm,
                                  int64_t esize) {
    return esize == 8
        ? rm_to_cm_t(static_cast<const double*>(rm), m, n, ldrm,
                     static_cast<double*>(cm), ldcm)
        : esize == 4
        ? rm_to_cm_t(static_cast<const float*>(rm), m, n, ldrm,
                     static_cast<float*>(cm), ldcm)
        : esize == 16
        ? rm_to_cm_t(static_cast<const c128*>(rm), m, n, ldrm,
                     static_cast<c128*>(cm), ldcm)
        : int64_t(-4);
}

// ---- f64 compatibility wrappers (pre-round-5 symbol names) ------------

int64_t st_bc_pack(const double* global, int64_t m, int64_t n, int64_t ldg,
                   int64_t nb, int64_t p, int64_t q, int64_t pi, int64_t qi,
                   double* local, int64_t lld) {
    return bc_pack_t(global, m, n, ldg, nb, p, q, pi, qi, local, lld);
}

int64_t st_bc_unpack(const double* local, int64_t m, int64_t n, int64_t ldg,
                     int64_t nb, int64_t p, int64_t q, int64_t pi,
                     int64_t qi, double* global, int64_t lld) {
    return bc_unpack_t(local, m, n, ldg, nb, p, q, pi, qi, global, lld);
}

int64_t st_tile_pack(const double* global, int64_t m, int64_t n,
                     int64_t ldg, int64_t nb, double* tiles) {
    return tile_pack_t(global, m, n, ldg, nb, tiles);
}

int64_t st_tile_unpack(const double* tiles, int64_t m, int64_t n,
                       int64_t ldg, int64_t nb, double* global) {
    return tile_unpack_t(tiles, m, n, ldg, nb, global);
}

int64_t st_colmajor_to_rowmajor(const double* cm, int64_t m, int64_t n,
                                int64_t ldcm, double* rm, int64_t ldrm) {
    return cm_to_rm_t(cm, m, n, ldcm, rm, ldrm);
}

int64_t st_rowmajor_to_colmajor(const double* rm, int64_t m, int64_t n,
                                int64_t ldrm, double* cm, int64_t ldcm) {
    return rm_to_cm_t(rm, m, n, ldrm, cm, ldcm);
}

}  // extern "C"
