// slate-tpu native host runtime: layout conversion kernels.
//
// TPU-native analog of the reference's data-interchange machinery:
//  - Matrix::fromScaLAPACK zero-copy wrapping of 2D block-cyclic buffers
//    (reference include/slate/Matrix.hh:73 and the scalapack_api/ layer,
//    e.g. scalapack_api/scalapack_potrf.cc:94-110 reading BLACS grids);
//  - the tile layout conversions (BaseMatrix.hh:551-603 col<->row major,
//    src/cuda/device_transpose.cu batched tile transpose).
//
// On TPU the device side needs none of this (XLA owns device layout), but
// the HOST side does: users arriving from ScaLAPACK hold per-process 2D
// block-cyclic local arrays, and staging those into the global row-major
// buffers jax.device_put expects is a memory-bound strided copy that
// belongs in native code. These kernels are exposed through ctypes
// (slate_tpu/interop/scalapack.py) and parallelized with OpenMP, matching
// the reference's use of OpenMP for host-side data motion.
//
// Layout conventions:
//  - global: row-major (m x n), leading dimension ldg >= n.
//  - block-cyclic local: TRUE ScaLAPACK layout. The (p, q) process at
//    coords (pi, qi) owns tiles (i, j) with i % p == pi, j % q == qi
//    (block-cyclic with source process 0, the BLACS default); its local
//    buffer is a COLUMN-MAJOR (lld x nloc) array with lld >= mloc =
//    numroc(m, nb, pi, p), exactly what Cpdgemr2d / pdpotrf_ expect and
//    what the reference wraps zero-copy in Matrix::fromScaLAPACK
//    (include/slate/Matrix.hh:347). Local row li maps to global row
//    (li/nb * p + pi) * nb + li%nb; ragged final blocks are NOT padded
//    (matching numroc), so buffers from real ScaLAPACK/BLACS programs
//    are byte-compatible.

#include <cstdint>
#include <cstring>
#include <algorithm>

#if defined(_OPENMP)
#include <omp.h>
#endif

extern "C" {

// Number of local tile-rows for grid coordinate pi of p over mt tiles.
static inline int64_t local_tiles(int64_t mt, int64_t p, int64_t pi) {
    return (mt - pi + p - 1) / p;
}

// ScaLAPACK numroc (TOOLS/numroc.f) with source process 0: how many of
// the m rows land on grid coordinate pi of p with block size nb.
int64_t st_numroc(int64_t m, int64_t nb, int64_t pi, int64_t p) {
    const int64_t nblocks = m / nb;
    int64_t loc = (nblocks / p) * nb;
    const int64_t extra = nblocks % p;
    if (pi < extra) loc += nb;
    else if (pi == extra) loc += m % nb;
    return loc;
}

// Pack a row-major global (m x n) matrix into one process's TRUE
// ScaLAPACK local buffer: column-major (lld x nloc), lld >= mloc =
// numroc(m, nb, pi, p). Returns 0 on success.
int64_t st_bc_pack(const double* global, int64_t m, int64_t n, int64_t ldg,
                   int64_t nb, int64_t p, int64_t q, int64_t pi, int64_t qi,
                   double* local, int64_t lld) {
    if (!global || !local || nb <= 0 || p <= 0 || q <= 0) return -1;
    if (pi < 0 || pi >= p || qi < 0 || qi >= q) return -2;
    if (lld < st_numroc(m, nb, pi, p)) return -3;
    const int64_t mt = (m + nb - 1) / nb;
    const int64_t nt = (n + nb - 1) / nb;
    const int64_t mtl = local_tiles(mt, p, pi);
    const int64_t ntl = local_tiles(nt, q, qi);
#pragma omp parallel for collapse(2) schedule(static)
    for (int64_t jl = 0; jl < ntl; ++jl) {
        for (int64_t il = 0; il < mtl; ++il) {
            const int64_t gi = pi + il * p;   // global tile row
            const int64_t gj = qi + jl * q;   // global tile col
            const int64_t r0 = gi * nb, c0 = gj * nb;
            const int64_t rows = std::min(nb, m - r0);
            const int64_t cols = std::min(nb, n - c0);
            for (int64_t c = 0; c < cols; ++c) {
                double* dst = local + (jl * nb + c) * lld + il * nb;
                const double* src = global + r0 * ldg + (c0 + c);
                for (int64_t r = 0; r < rows; ++r)
                    dst[r] = src[r * ldg];
            }
        }
    }
    return 0;
}

// Inverse of st_bc_pack: scatter one process's ScaLAPACK column-major
// local buffer back into the row-major global matrix (only this
// process's entries are written).
int64_t st_bc_unpack(const double* local, int64_t m, int64_t n, int64_t ldg,
                     int64_t nb, int64_t p, int64_t q, int64_t pi,
                     int64_t qi, double* global, int64_t lld) {
    if (!global || !local || nb <= 0 || p <= 0 || q <= 0) return -1;
    if (pi < 0 || pi >= p || qi < 0 || qi >= q) return -2;
    if (lld < st_numroc(m, nb, pi, p)) return -3;
    const int64_t mt = (m + nb - 1) / nb;
    const int64_t nt = (n + nb - 1) / nb;
    const int64_t mtl = local_tiles(mt, p, pi);
    const int64_t ntl = local_tiles(nt, q, qi);
#pragma omp parallel for collapse(2) schedule(static)
    for (int64_t jl = 0; jl < ntl; ++jl) {
        for (int64_t il = 0; il < mtl; ++il) {
            const int64_t gi = pi + il * p;
            const int64_t gj = qi + jl * q;
            const int64_t r0 = gi * nb, c0 = gj * nb;
            const int64_t rows = std::min(nb, m - r0);
            const int64_t cols = std::min(nb, n - c0);
            for (int64_t c = 0; c < cols; ++c) {
                const double* src = local + (jl * nb + c) * lld + il * nb;
                double* dst = global + r0 * ldg + (c0 + c);
                for (int64_t r = 0; r < rows; ++r)
                    dst[r * ldg] = src[r];
            }
        }
    }
    return 0;
}

// Pack a row-major global matrix into tile-major (mt, nt, nb, nb) order
// (padded). The host-side analog of the reference's tile layout
// (Tile.hh + MatrixStorage tile map) used for fast staging.
int64_t st_tile_pack(const double* global, int64_t m, int64_t n,
                     int64_t ldg, int64_t nb, double* tiles) {
    if (!global || !tiles || nb <= 0) return -1;
    const int64_t mt = (m + nb - 1) / nb;
    const int64_t nt = (n + nb - 1) / nb;
#pragma omp parallel for collapse(2) schedule(static)
    for (int64_t i = 0; i < mt; ++i) {
        for (int64_t j = 0; j < nt; ++j) {
            const int64_t r0 = i * nb, c0 = j * nb;
            const int64_t rows = std::min(nb, m - r0);
            const int64_t cols = std::min(nb, n - c0);
            double* t = tiles + ((i * nt) + j) * nb * nb;
            for (int64_t r = 0; r < rows; ++r) {
                std::memcpy(t + r * nb, global + (r0 + r) * ldg + c0,
                            size_t(cols) * sizeof(double));
                if (cols < nb)
                    std::memset(t + r * nb + cols, 0,
                                size_t(nb - cols) * sizeof(double));
            }
            for (int64_t r = rows; r < nb; ++r)
                std::memset(t + r * nb, 0, size_t(nb) * sizeof(double));
        }
    }
    return 0;
}

int64_t st_tile_unpack(const double* tiles, int64_t m, int64_t n,
                       int64_t ldg, int64_t nb, double* global) {
    if (!global || !tiles || nb <= 0) return -1;
    const int64_t mt = (m + nb - 1) / nb;
    const int64_t nt = (n + nb - 1) / nb;
#pragma omp parallel for collapse(2) schedule(static)
    for (int64_t i = 0; i < mt; ++i) {
        for (int64_t j = 0; j < nt; ++j) {
            const int64_t r0 = i * nb, c0 = j * nb;
            const int64_t rows = std::min(nb, m - r0);
            const int64_t cols = std::min(nb, n - c0);
            const double* t = tiles + ((i * nt) + j) * nb * nb;
            for (int64_t r = 0; r < rows; ++r)
                std::memcpy(global + (r0 + r) * ldg + c0, t + r * nb,
                            size_t(cols) * sizeof(double));
        }
    }
    return 0;
}

// Column-major (LAPACK/ScaLAPACK) <-> row-major conversion with OpenMP
// blocking (the host analog of device_transpose.cu).
int64_t st_colmajor_to_rowmajor(const double* cm, int64_t m, int64_t n,
                                int64_t ldcm, double* rm, int64_t ldrm) {
    if (!cm || !rm) return -1;
    const int64_t B = 64;
#pragma omp parallel for collapse(2) schedule(static)
    for (int64_t ib = 0; ib < m; ib += B) {
        for (int64_t jb = 0; jb < n; jb += B) {
            const int64_t ie = std::min(ib + B, m);
            const int64_t je = std::min(jb + B, n);
            for (int64_t j = jb; j < je; ++j)
                for (int64_t i = ib; i < ie; ++i)
                    rm[i * ldrm + j] = cm[j * ldcm + i];
        }
    }
    return 0;
}

int64_t st_rowmajor_to_colmajor(const double* rm, int64_t m, int64_t n,
                                int64_t ldrm, double* cm, int64_t ldcm) {
    if (!rm || !cm) return -1;
    const int64_t B = 64;
#pragma omp parallel for collapse(2) schedule(static)
    for (int64_t ib = 0; ib < m; ib += B) {
        for (int64_t jb = 0; jb < n; jb += B) {
            const int64_t ie = std::min(ib + B, m);
            const int64_t je = std::min(jb + B, n);
            for (int64_t i = ib; i < ie; ++i)
                for (int64_t j = jb; j < je; ++j)
                    cm[j * ldcm + i] = rm[i * ldrm + j];
        }
    }
    return 0;
}

}  // extern "C"
