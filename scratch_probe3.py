"""Probe new factorization drivers on TPU: potrf/getrf/geqrf rates."""
import sys
import time
import jax
import jax.numpy as jnp
import bench

n = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
nb = 512
which = sys.argv[2] if len(sys.argv) > 2 else "all"


def probe_potrf():
    import slate_tpu as st
    from slate_tpu.core.types import Uplo
    from slate_tpu.matgen import random_spd
    from slate_tpu.linalg.cholesky import _potrf_blocked
    a = random_spd(n, dtype=jnp.float32, seed=3)

    def step(a_data, cs):
        with jax.default_matmul_precision("highest"):
            l, info = _potrf_blocked(a_data, nb, n // nb, prec="high")
        return a_data + 1e-30 * l

    t0 = time.perf_counter()
    t = bench._per_iter_seconds(step, a, (), k1=2, k2=6)
    print(f"potrf  n={n}: {(n**3/3)/1e9/t:9.1f} GFLOP/s ({t*1e3:.2f} ms) "
          f"[probe wall {time.perf_counter()-t0:.0f}s]")


def probe_getrf():
    from slate_tpu.linalg.lu import _getrf_blocked
    a = jax.random.normal(jax.random.key(0), (n, n), jnp.float32) \
        + n * jnp.eye(n, dtype=jnp.float32) * 0  # general matrix

    def step(a_data, cs):
        with jax.default_matmul_precision("highest"):
            lu, perm, info = _getrf_blocked(a_data, nb, n // nb, prec="high")
        return a_data + 1e-30 * lu

    t0 = time.perf_counter()
    t = bench._per_iter_seconds(step, a, (), k1=2, k2=6)
    print(f"getrf  n={n}: {(2*n**3/3)/1e9/t:9.1f} GFLOP/s ({t*1e3:.2f} ms) "
          f"[probe wall {time.perf_counter()-t0:.0f}s]")


def probe_geqrf():
    import slate_tpu as st

    a = jax.random.normal(jax.random.key(0), (n, n), jnp.float32)
    A = st.from_dense(a, nb=nb)

    def step(a_data, cs):
        (A,) = cs
        qr = st.geqrf(A.with_data(a_data))
        return a_data + 1e-30 * qr.vr

    t0 = time.perf_counter()
    t = bench._per_iter_seconds(step, A.data, (A,), k1=2, k2=6)
    print(f"geqrf  n={n}: {(4*n**3/3)/1e9/t:9.1f} GFLOP/s ({t*1e3:.2f} ms) "
          f"[probe wall {time.perf_counter()-t0:.0f}s]")


if which in ("all", "potrf"):
    probe_potrf()
if which in ("all", "getrf"):
    probe_getrf()
if which in ("all", "geqrf"):
    probe_geqrf()
