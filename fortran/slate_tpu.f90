! slate-tpu Fortran API: iso_c_binding interfaces over the C API
! (native/capi.c / include/slate_tpu_capi.h).
!
! Reference analog: the generated Fortran module of tools/fortran/ in
! SLATE. Same conventions as the C API: column-major double-precision
! arrays with leading dimensions, LAPACK argument order, info as the
! function result (0 success, >0 numerical, <0 runtime failure).
!
! Build (needs a Fortran compiler; this image ships none, so the module
! is compile-tested only where gfortran exists — tests/test_compat.py
! skips otherwise):
!
!     gfortran -c slate_tpu.f90
!     gfortran main.f90 slate_tpu.o -L../native -lslate_tpu_capi
!
! Usage:
!
!     use slate_tpu
!     integer(c_int64_t) :: info
!     info = slate_tpu_dgesv(n, nrhs, a, lda, ipiv, b, ldb)

module slate_tpu
   use iso_c_binding, only: c_int64_t, c_double, c_char
   implicit none

   interface
      function slate_tpu_dgesv(n, nrhs, a, lda, ipiv, b, ldb) &
            bind(c, name="slate_tpu_dgesv") result(info)
         import :: c_int64_t, c_double
         integer(c_int64_t), value :: n, nrhs, lda, ldb
         real(c_double), intent(inout) :: a(lda, *), b(ldb, *)
         integer(c_int64_t), intent(out) :: ipiv(*)
         integer(c_int64_t) :: info
      end function

      function slate_tpu_dpotrf(uplo, n, a, lda) &
            bind(c, name="slate_tpu_dpotrf") result(info)
         import :: c_int64_t, c_double, c_char
         character(kind=c_char), intent(in) :: uplo(*)
         integer(c_int64_t), value :: n, lda
         real(c_double), intent(inout) :: a(lda, *)
         integer(c_int64_t) :: info
      end function

      function slate_tpu_dposv(uplo, n, nrhs, a, lda, b, ldb) &
            bind(c, name="slate_tpu_dposv") result(info)
         import :: c_int64_t, c_double, c_char
         character(kind=c_char), intent(in) :: uplo(*)
         integer(c_int64_t), value :: n, nrhs, lda, ldb
         real(c_double), intent(inout) :: a(lda, *), b(ldb, *)
         integer(c_int64_t) :: info
      end function

      function slate_tpu_dgels(m, n, nrhs, a, lda, b, ldb) &
            bind(c, name="slate_tpu_dgels") result(info)
         import :: c_int64_t, c_double
         integer(c_int64_t), value :: m, n, nrhs, lda, ldb
         real(c_double), intent(inout) :: a(lda, *), b(ldb, *)
         integer(c_int64_t) :: info
      end function

      function slate_tpu_dgetrf(m, n, a, lda, ipiv) &
            bind(c, name="slate_tpu_dgetrf") result(info)
         import :: c_int64_t, c_double
         integer(c_int64_t), value :: m, n, lda
         real(c_double), intent(inout) :: a(lda, *)
         integer(c_int64_t), intent(out) :: ipiv(*)
         integer(c_int64_t) :: info
      end function

      function slate_tpu_dgetrs(trans, n, nrhs, a, lda, ipiv, b, ldb) &
            bind(c, name="slate_tpu_dgetrs") result(info)
         import :: c_int64_t, c_double, c_char
         character(kind=c_char), intent(in) :: trans(*)
         integer(c_int64_t), value :: n, nrhs, lda, ldb
         real(c_double), intent(inout) :: a(lda, *), b(ldb, *)
         integer(c_int64_t), intent(in) :: ipiv(*)
         integer(c_int64_t) :: info
      end function

      function slate_tpu_dpotrs(uplo, n, nrhs, a, lda, b, ldb) &
            bind(c, name="slate_tpu_dpotrs") result(info)
         import :: c_int64_t, c_double, c_char
         character(kind=c_char), intent(in) :: uplo(*)
         integer(c_int64_t), value :: n, nrhs, lda, ldb
         real(c_double), intent(inout) :: a(lda, *), b(ldb, *)
         integer(c_int64_t) :: info
      end function

      function slate_tpu_dsyev(jobz, uplo, n, a, lda, w) &
            bind(c, name="slate_tpu_dsyev") result(info)
         import :: c_int64_t, c_double, c_char
         character(kind=c_char), intent(in) :: jobz(*), uplo(*)
         integer(c_int64_t), value :: n, lda
         real(c_double), intent(inout) :: a(lda, *)
         real(c_double), intent(out) :: w(*)
         integer(c_int64_t) :: info
      end function

      function slate_tpu_dgesvd(jobu, jobvt, m, n, a, lda, s, u, ldu, &
                                vt, ldvt) &
            bind(c, name="slate_tpu_dgesvd") result(info)
         import :: c_int64_t, c_double, c_char
         character(kind=c_char), intent(in) :: jobu(*), jobvt(*)
         integer(c_int64_t), value :: m, n, lda, ldu, ldvt
         real(c_double), intent(inout) :: a(lda, *)
         real(c_double), intent(out) :: s(*), u(ldu, *), vt(ldvt, *)
         integer(c_int64_t) :: info
      end function

      function slate_tpu_dgemm(transa, transb, m, n, k, alpha, a, lda, &
                               b, ldb, beta, c, ldc) &
            bind(c, name="slate_tpu_dgemm") result(info)
         import :: c_int64_t, c_double, c_char
         character(kind=c_char), intent(in) :: transa(*), transb(*)
         integer(c_int64_t), value :: m, n, k, lda, ldb, ldc
         real(c_double), value :: alpha, beta
         real(c_double), intent(in) :: a(lda, *), b(ldb, *)
         real(c_double), intent(inout) :: c(ldc, *)
         integer(c_int64_t) :: info
      end function

      function slate_tpu_dtrsm(side, uplo, transa, diag, m, n, alpha, &
                               a, lda, b, ldb) &
            bind(c, name="slate_tpu_dtrsm") result(info)
         import :: c_int64_t, c_double, c_char
         character(kind=c_char), intent(in) :: side(*), uplo(*)
         character(kind=c_char), intent(in) :: transa(*), diag(*)
         integer(c_int64_t), value :: m, n, lda, ldb
         real(c_double), value :: alpha
         real(c_double), intent(in) :: a(lda, *)
         real(c_double), intent(inout) :: b(ldb, *)
         integer(c_int64_t) :: info
      end function

      function slate_tpu_dlange(norm, m, n, a, lda) &
            bind(c, name="slate_tpu_dlange") result(val)
         import :: c_int64_t, c_double, c_char
         character(kind=c_char), intent(in) :: norm(*)
         integer(c_int64_t), value :: m, n, lda
         real(c_double), intent(in) :: a(lda, *)
         real(c_double) :: val
      end function
   end interface

end module slate_tpu
