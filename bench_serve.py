#!/usr/bin/env python
"""Serving benchmark: resident-factor cached solves vs per-request
factor+solve.

Drives the slate_tpu.runtime stack end to end — Session (HBM-budget
factor cache) + Executor (batching, AOT warmup) — against the naive
baseline every caller pays today: one full factor+solve per request.
The headline is the throughput ratio; the artifact also records the
serving percentiles and cache hit-rate the runtime's Metrics export.

Artifact schema (JSON, one object; see PERF.md "bench_serve artifact"):
  {"bench": "serve", "backend": ..., "dtype": ...,
   "n": int, "nb": int, "requests": int, "max_batch": int,
   "serve":       {"wall_s", "solves_per_sec", "p50_ms", "p99_ms",
                   "cache_hit_rate", "batches", "gflops"},
   "per_request": {"wall_s", "solves_per_sec"},
   "speedup": serve.solves_per_sec / per_request.solves_per_sec}

--smoke: small shapes on CPU, <60 s, exit 0 iff the artifact was
written and cached-factor serving beat per-request factor+solve
(speedup > 1) — wired into examples/run_tests.py.

--batched (round 10): the many-small-problems A/B — B independent
small systems served as ONE batched program (api.gesv_batched /
posv_batched through the pow2 batch-bucket engine) vs B per-request
programs (the same engine at B=1 per call). Emits one
``serve_batched`` row per (op, n, B) combo to ``--batched-out``
(BENCH_r08.json) — a JSON LIST that tools/bench_gate.py normalizes and
gates per (metric, platform, n, batch) series. The per-request arm is
measured on a bounded sample at large B (recorded in the row); the
throughput claim on CPU is SMOKE ONLY — in-op batch parallelism is a
TPU lowering property, backed structurally by the rows'
``hlo_one_program`` flag (no per-item factorization custom-call loop
in the batched program, same evidence class as rounds 6–7).

--multichip (round 11): the pod-scale serving A/B — factor once on a
p×q mesh and serve N solves from the MESH-SHARDED resident factor
(``Session(mesh=...)`` + Batcher) vs the same N solves from a
single-device session. Writes the structured ``MULTICHIP_r*.json``
artifact: ``{"bench": "multichip", "platform", "mesh_shape",
"n_devices", "rows": [...]}`` — the machine-readable successor of the
r01–r05 ``{n_devices, rc, ok, tail}`` dry-run blobs (whose metrics
were buried in a text tail). Each row records both arms' solves/sec,
the served solve program's collective census (scheduled-HLO evidence
the solve really runs sharded — nonzero counts/bytes), the measured
ICI bytes credited per served solve, the per-chip vs total resident
bytes of the sharded factor, and a one-program flag (repeat solves
added no compiles). Run on the forced 8-device CPU mesh this is
honestly labeled dispatch-bound smoke (the standing tunnel caveat);
the structural columns are the portable claim.
"""

import argparse
import json
import math
import os
import re
import sys
import time

import numpy as np

from slate_tpu.compat.platform import apply_env_platforms

apply_env_platforms()

# Every top-level section the serve artifact currently carries — ONE
# source of truth shared with tools/bench_gate.py since round 22
# (tools/serve_sections.py; the drift pin is now an import-identity
# test). bench() asserts it at write time; --check-schema asserts it
# on the committed files; --regen-smoke is the guarded regeneration
# path.


def _load_serve_sections():
    """Load tools/serve_sections.py under ONE fixed module name (both
    consumers share the cached module, so the tuples are the SAME
    object — the import-identity pin)."""
    import importlib.util
    name = "slate_tpu_serve_sections"
    mod = sys.modules.get(name)
    if mod is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "tools", "serve_sections.py")
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
    return mod


SERVE_ARTIFACT_SECTIONS = _load_serve_sections().SERVE_ARTIFACT_SECTIONS


def _tenants_section(sess):
    """The serve artifact's round-15 ``tenants`` section: per-tenant
    totals + placement rows + the conservation verdict (exit-gated —
    a bench run whose attribution stopped summing to the globals is a
    broken ledger, not a slow one)."""
    from slate_tpu.obs.attribution import CLASSES

    snap = sess.attribution.snapshot()
    conservation = {
        cls: {"per_tenant_sum": snap["totals"].get(cls, 0.0),
              "global": sess.metrics.get(counter),
              "ok": snap["totals"].get(cls, 0.0)
              == sess.metrics.get(counter)}
        for cls, counter in CLASSES.items()
    }
    placement = sess.placement_snapshot(host="bench")
    return {
        "enabled": True,
        "halflife_s": snap["halflife_s"],
        "per_tenant": {t: row["totals"]
                       for t, row in snap["tenants"].items()},
        "conservation": conservation,
        "conservation_ok": all(c["ok"] for c in conservation.values()),
        "placement": placement,
    }


def _numerics_section(sess):
    """The serve artifact's round-16 ``numerics`` section: the
    per-handle health rows (condest / growth / sampled-residual EWMA /
    state), the probe counters, and the exit-gated verdict — the bench
    operand is a well-conditioned SPD matrix, so every handle must
    classify healthy, the condest must be a finite positive estimate,
    and the sampled probes must have fired (deterministic sampler, so
    a zero count means the seam went dead, not bad luck)."""
    payload = sess.numerics_payload()
    handles = payload.get("handles", {})
    counters = payload.get("counters", {})
    conds = [row.get("condest") for row in handles.values()
             if row.get("condest") is not None]
    ok = (bool(handles)
          and all(row["state"] == "healthy" for row in handles.values())
          and bool(conds)
          and all(0.0 < c < float("inf") for c in conds)
          and counters.get("residual_probes_total", 0) > 0
          and counters.get("condest_runs_total", 0) > 0
          and counters.get("numerics_nonfinite_total", 0) == 0)
    return {
        "enabled": True,
        "handles": handles,
        "counts": payload.get("counts", {}),
        "counters": counters,
        "sample_fraction": payload.get("config", {}).get(
            "sample_fraction"),
        "ok": ok,
    }


def _apply_dot_census(sess):
    """dot-op counts of every warmed spectral apply program, by
    function name — the round-19 two-gemm pin (each served matrix
    function lowers to exactly two gemms + a diagonal scale)."""
    dots = {}
    for key, exe in sess._compiled.items():
        if isinstance(key, tuple) and key \
                and key[0] == "spectral.apply":
            dots[key[1]] = len(re.findall(r"dot\(", exe.as_text()))
    return dots


def _spectral_section(sess, dtype):
    """The serve artifact's round-19 ``spectral`` section: a resident
    eigendecomposition registered in the SAME bench session, warmed,
    and served through every catalog function — recording the
    structural columns of the spectral serving claim (zero new
    compiles across theta-varying serves, the two-gemm dot census of
    each warmed apply program, the staged factor programs in the
    cost log) plus a solve-with-shift accuracy spot check. Sized
    small (n=96) so the section is schema/structure evidence, not a
    second headline — the throughput A/B lives in --spectral
    (BENCH_SPECTRAL_r*.json)."""
    import slate_tpu as st
    from slate_tpu import spectral as sp

    ns, nbs = 96, 32
    rng = np.random.default_rng(19)
    a = rng.standard_normal((ns, ns)).astype(dtype)
    a = ((a + a.T) / 2).astype(dtype)
    A = st.from_dense(a, nb=nbs, kind=st.MatrixKind.Hermitian)
    h = sess.register(A, op="eig", tenant="bench-a")
    sess.warmup(h, nrhs=1)
    n_compiles = len(sess.compile_log)
    fns = sorted(sp.function_catalog("eig"))
    b = rng.standard_normal(ns).astype(dtype)
    shift = 0.7
    x = None
    for fn in fns:
        for theta in (0.0, shift):
            y = sess.apply(h, b, fn=fn, theta=theta, tenant="bench-a")
            if fn == "solve" and theta == shift:
                x = y
    new_compiles = len(sess.compile_log) - n_compiles
    dots = _apply_dot_census(sess)
    lam = sess.eigvals(h)
    xd = np.linalg.solve(a.astype(np.float64) - shift * np.eye(ns), b)
    rel = float(np.abs(x - xd).max() / max(np.abs(xd).max(), 1.0))
    stages = [r["what"] for r in sess.cost_log
              if r["what"].startswith("spectral.")]
    ok = (new_compiles == 0
          and bool(dots) and all(v == 2 for v in dots.values())
          and rel < (1e-3 if np.dtype(dtype).itemsize <= 4 else 1e-8)
          and lam.shape == (ns,))
    return {
        "enabled": True, "op": "eig", "n": ns, "nb": nbs,
        "functions": fns,
        "new_compiles_after_warmup": new_compiles,
        "apply_dot_ops": dots,
        "stage_programs": stages,
        "solve_rel_err": rel,
        "ok": ok,
    }


def _updates_section(sess, dtype):
    """The serve artifact's round-20 ``updates`` section: a resident
    Cholesky registered in the SAME bench session, warmed with
    ``update_k``, then served two rank-k operand mutations through
    the incremental-maintenance verb — recording the structural
    columns of the update claim (every mutation applied on the O(n²k)
    path, zero full refactors, zero new compiles after warmup,
    nonzero update-flops credited to the ledger) plus a
    post-mutation solve accuracy spot check against the accumulated
    dense operand. Sized small (n=96) so the section is
    schema/structure evidence, not a second headline — the
    updates/s-vs-refactors/s A/B lives in --updates
    (BENCH_UPDATE_r*.json)."""
    import slate_tpu as st

    ns, nbs, k = 96, 32, 2
    rng = np.random.default_rng(20)
    a = rng.standard_normal((ns, ns)).astype(dtype)
    spd = (a @ a.T + ns * np.eye(ns)).astype(dtype)
    A = st.hermitian(np.tril(spd), nb=nbs, uplo=st.Uplo.Lower)
    h = sess.register(A, op="chol", tenant="bench-a")
    sess.warmup(h, nrhs=1, update_k=k)
    snap0 = sess.metrics.snapshot()["counters"]
    nc0 = len(sess.compile_log)
    acc = spd.astype(np.float64)
    results = []
    for _ in range(2):
        w = (0.05 * rng.standard_normal((ns, k))).astype(dtype)
        out = sess.update(h, w, tenant="bench-a")
        w64 = w.astype(np.float64)
        acc = acc + w64 @ w64.T
        results.append(out)
    new_compiles = len(sess.compile_log) - nc0
    b = rng.standard_normal(ns).astype(dtype)
    x = sess.solve(h, b, tenant="bench-a")
    xd = np.linalg.solve(acc, b.astype(np.float64))
    rel = float(np.abs(np.asarray(x, np.float64).ravel() - xd).max()
                / max(np.abs(xd).max(), 1.0))
    snap1 = sess.metrics.snapshot()["counters"]

    def d(key):
        return snap1.get(key, 0) - snap0.get(key, 0)

    ok = (all(r["applied"] for r in results)
          and new_compiles == 0
          and d("update_refactors_total") == 0
          and d("factors_total") == 0
          and d("updates_total") == 2
          and d("update_flops_total") > 0
          and rel < (1e-3 if np.dtype(dtype).itemsize <= 4 else 1e-8))
    return {
        "enabled": True, "op": "chol", "n": ns, "nb": nbs, "k": k,
        "updates_applied": sum(bool(r["applied"]) for r in results),
        "new_compiles_after_warmup": new_compiles,
        "update_refactors": d("update_refactors_total"),
        "refactors_during_updates": d("factors_total"),
        "update_flops": d("update_flops_total"),
        "solve_rel_err": rel,
        "ok": ok,
    }


def _tuning_section(sess, dtype):
    """The serve artifact's round-21 ``tuning`` section: structural
    evidence the committed tuning table wires end to end — the table
    loads and validates, a fresh operator registered through it
    resolves its config with provenance recorded on the entry, and a
    warmed tuned solve adds NO compiles on the serve path (exit-gated
    ok). Runs after the timed window (the headline serve numbers stay
    table-free — the A/B that measures the table is ``--tuned``); the
    table activation is restored before returning so the rest of the
    artifact build sees the untuned process state."""
    import slate_tpu as st
    from slate_tpu import tuning as tn

    path = tn.table_path()
    if not os.path.exists(path):
        return {"enabled": False, "table": None, "resolved": None,
                "new_compiles_after_warmup": None, "ok": True}
    import jax
    table = tn.TuningTable.from_path(path)
    backend = jax.default_backend()
    platform_row = any(e.get("platform") in ("*", backend)
                       for e in table.entries)
    prev_tbl = tn.activate_table(table)
    prev_sess = sess.tuning
    sess.tuning = table
    try:
        ns, nbs = 32, 8
        rng = np.random.default_rng(21)
        a = rng.standard_normal((ns, ns)).astype(dtype)
        spd = a @ a.T + ns * np.eye(ns, dtype=dtype)
        A = st.hermitian(np.tril(spd), nb=nbs, uplo=st.Uplo.Lower)
        h = sess.register(A, op="chol", tenant="bench-a")
        resolved = sess._ops[h].tuned
        sess.warmup(h)
        nc0 = len(sess.compile_log)
        b = rng.standard_normal(ns).astype(dtype)
        x = sess.solve(h, b, tenant="bench-a")
        new_compiles = len(sess.compile_log) - nc0
        xd = np.linalg.solve(spd.astype(np.float64),
                             b.astype(np.float64))
        rel = float(np.abs(np.asarray(x, np.float64).ravel() - xd).max()
                    / max(np.abs(xd).max(), 1.0))
        ok = (new_compiles == 0 and rel < 1e-3
              and (resolved is not None or not platform_row))
        return {
            "enabled": True,
            "table": {"file": os.path.basename(path),
                      "schema": tn.TUNING_SCHEMA,
                      "entries": len(table.entries),
                      "platform_match": platform_row},
            "resolved": resolved,
            "op": "chol", "n": ns,
            "new_compiles_after_warmup": new_compiles,
            "solve_rel_err": rel,
            "ok": ok,
        }
    finally:
        sess.tuning = prev_sess
        tn.activate_table(prev_tbl)


def _build_operator(n, nb, dtype):
    import slate_tpu as st

    rng = np.random.default_rng(7)
    a = rng.standard_normal((n, n)).astype(dtype)
    spd = a @ a.T + n * np.eye(n, dtype=dtype)
    A = st.hermitian(np.tril(spd), nb=nb, uplo=st.Uplo.Lower)
    return A, spd


def _incidents_section(sess, handle):
    """The serve artifact's round-22 ``incidents`` section: the
    decision-journal/counter parity table over every kind that fired
    during this exact workload, plus one deliberately-triggered probe
    incident validated against ``slate_tpu.incident.v1`` (exit-gated —
    a bench whose black box stopped recording, or whose journal
    drifted from its counters, is a broken recorder, not a slow
    bench). The committed fixture's ``sample`` doc is what bench_gate
    --check-schema's jax-free mirror validator chews on."""
    from slate_tpu.obs import validate_incident
    from slate_tpu.obs.events import KIND_COUNTERS

    rec = sess.recorder
    if rec is None:
        return {"enabled": False, "ok": False}
    sample = rec.incident("bench_probe", key="bench", handle=handle,
                          context={"bench": "serve"})
    errs = [] if sample is None else validate_incident(sample)
    counters = sess.metrics.snapshot()["counters"]
    counts = rec.journal.counts()
    parity = {}
    for kind, counter in sorted(KIND_COUNTERS.items()):
        j = counts.get(kind, 0.0)
        c = counters.get(counter, 0.0)
        if j or c:
            parity[kind] = {"journal": j, "counter": c, "ok": j == c}
    if not parity:
        # a perfectly quiet run still records the (vacuously-equal)
        # eviction row so the gate's parity table is never empty
        parity["eviction"] = {
            "journal": counts.get("eviction", 0.0),
            "counter": counters.get("evictions", 0.0),
            "ok": counts.get("eviction", 0.0)
            == counters.get("evictions", 0.0)}
    ok = (sample is not None and not errs
          and all(r["ok"] for r in parity.values()))
    return {
        "enabled": True,
        "ok": ok,
        "captured": counters.get("incidents_captured_total", 0.0),
        "journal_recorded": rec.journal.payload()["recorded"],
        "journal_digest": rec.journal.digest(),
        "parity": parity,
        "validator_errors": errs,
        "sample": sample,
    }


def _forecast_section(sess):
    """The serve artifact's round-23 ``forecast`` section: the
    telemetry-history view of this exact workload — the full
    ``slate_tpu.timeseries.v1`` store payload (what /history serves),
    the ``slate_tpu.forecast.v1`` document over it (what /forecast
    serves), and the counter-conservation table: every counter series'
    lifetime delta sum must equal the live metric counter EXACTLY
    (the store records deltas; their sum reconstructs the cumulative
    value bit-for-bit). Exit-gated — a serving bench whose sensing
    substrate stopped sampling, stopped validating, or lost a count is
    a broken forecaster, not a slow bench. The embedded payloads are
    what bench_gate --check-schema's file-loaded validators chew on."""
    from slate_tpu.obs import validate_forecast, validate_timeseries

    store = sess.timeseries
    if store is None:
        return {"enabled": False, "ok": False}
    # final forced pump: the conservation check below compares against
    # a counter snapshot taken AFTER this (nothing runs in between —
    # the executor is closed and every other section already built)
    sess.pump_timeseries(force=True)
    history = store.payload()
    hist_errs = validate_timeseries(history)
    forecast = sess.forecaster.payload(horizon_s=60.0, k=4,
                                       max_series=48, points_limit=8)
    fc_errs = validate_forecast(forecast)
    counters = sess.metrics.snapshot()["counters"]
    conservation = {}
    for name, total in sorted(store.counter_totals().items()):
        live = counters.get(name, 0.0)
        conservation[name] = {"store": total, "counter": live,
                              "ok": total == live}
    ok = (not hist_errs and not fc_errs
          and history["series_count"] > 0
          and bool(conservation)
          and all(r["ok"] for r in conservation.values()))
    return {
        "enabled": True,
        "ok": ok,
        "series_count": history["series_count"],
        "dropped_series": history["dropped_series"],
        "dropped_samples": history["dropped_samples"],
        "conservation": conservation,
        "history": history,
        "forecast": forecast,
        "validator_errors": hist_errs + fc_errs,
    }


def bench(n=512, nb=128, requests=64, max_batch=16, max_wait=1e-3,
          dtype=np.float32, out_path="BENCH_SERVE.json"):
    import jax

    import slate_tpu as st
    from slate_tpu.runtime import Executor, Session

    A, spd = _build_operator(n, nb, dtype)
    rng = np.random.default_rng(11)
    rhs = [rng.standard_normal(n).astype(dtype) for _ in range(requests)]

    # -- baseline: factor+solve per request (what callers pay today) ------
    def per_request_solve(b):
        X, info = st.posv(A, st.from_dense(b[:, None], nb=nb))
        return jax.block_until_ready(X.data)

    per_request_solve(rhs[0])  # warm the compile caches
    t0 = time.perf_counter()
    for b in rhs:
        per_request_solve(b)
    per_request_wall = time.perf_counter() - t0

    # -- serving runtime: resident factor + batched dispatch --------------
    # round 18: a declared tenant table through the bench — the
    # artifact's "quotas" section records the policy view (weights,
    # sub-budgets, live resident bytes) of this exact workload and the
    # quota counters (all zero here: the bench runs inside its limits
    # — the A/B that exercises enforcement is --tenants-fair)
    from slate_tpu.runtime import TenantPolicy
    sess = Session(hbm_budget=1 << 30, tenant_policies={
        "bench-a": TenantPolicy(weight=2.0),
        "bench-b": TenantPolicy(weight=1.0)})
    # round 12: SLO tracking through the bench — the artifact then
    # records what a production scrape of /slo would have said about
    # this exact workload (burn rates per objective, breach states)
    sess.enable_slo()
    # round 15: tenant attribution through the bench — the artifact's
    # "tenants" section records the per-tenant ledger view of this
    # exact workload (two tenants split the request stream) plus the
    # placement snapshot and the conservation check, exit-gated below
    sess.enable_attribution()
    # round 16: numerical-health telemetry through the bench — a high
    # deterministic sample fraction so the smoke run exercises the
    # probed-solve path; the artifact's "numerics" section records the
    # per-handle health view of this exact workload, exit-gated below
    sess.enable_numerics(sample_fraction=0.25, sample_seed=16)
    # round 22: the flight recorder + decision journal through the
    # bench — enabled BEFORE any decision seam can fire, so the
    # artifact's "incidents" section can check journal/counter parity
    # as absolute equality (both start at zero together)
    sess.enable_recorder()
    # round 23: the telemetry time-series store through the bench —
    # the sampler pumps (throttled) as results drain, so the
    # artifact's "forecast" section records the history-and-forecast
    # view of this exact workload, exit-gated below
    sess.enable_timeseries(interval_s=0.25)
    h = sess.register(A, op="chol", tenant="bench-a")
    with Executor(sess, max_batch=max_batch, max_wait=max_wait) as ex:
        ex.warmup([h])  # factor + AOT compile off the request path
        t0 = time.perf_counter()
        futs = [ex.submit(h, b, tenant=("bench-b" if i % 4 == 3
                                        else None))
                for i, b in enumerate(rhs)]
        xs = []
        for f in futs:
            xs.append(f.result(timeout=600))
            sess.pump_timeseries()  # <=1 sampling pass per 0.25 s
        serve_wall = time.perf_counter() - t0

    # correctness spot check (serving a wrong answer fast is not a win)
    resid = max(float(np.abs(spd @ x - b).max()) / n
                for x, b in zip(xs[:4], rhs[:4]))
    if not resid < 1e-2:
        raise RuntimeError(f"serving residual too large: {resid}")

    snap = sess.metrics.snapshot()
    lat = snap["histograms"].get("request_latency", {})
    # round 19: the resident-spectral structural exercise runs AFTER
    # the timed serve window (the snapshot above keeps the headline
    # percentiles spectral-free); the tenants/numerics sections below
    # are built after it, so its handle and probes fold into both
    spectral_section = _spectral_section(sess, dtype)
    # round 20: the incremental-maintenance structural exercise also
    # runs after the timed window, before the tenants/numerics
    # sections are built (its handle, updates and probes fold in)
    updates_section = _updates_section(sess, dtype)
    # round 21: the tuning-table structural exercise — committed table
    # loads, register-time resolution records provenance, warmed tuned
    # solve adds zero compiles; the timed window above stays table-free
    tuning_section = _tuning_section(sess, dtype)
    # round 22: built LAST so every decision the exercises above made
    # (evictions, update refactors, ...) is inside the parity check
    incidents_section = _incidents_section(sess, h)
    # round 23: built after incidents (its probe capture bumps
    # counters) so the final forced pump sees every count this run
    # will ever make — the conservation table then holds exactly
    forecast_section = _forecast_section(sess)
    artifact = {
        "bench": "serve",
        "backend": jax.devices()[0].platform,
        "dtype": np.dtype(dtype).name,
        "n": n, "nb": nb, "requests": requests, "max_batch": max_batch,
        "serve": {
            "wall_s": serve_wall,
            "solves_per_sec": requests / serve_wall,
            "p50_ms": lat.get("p50", 0.0) * 1e3,
            "p99_ms": lat.get("p99", 0.0) * 1e3,
            "cache_hit_rate": snap["derived"]["cache_hit_rate"],
            "batches": snap["counters"].get("batches_total", 0),
            "gflops": snap["derived"]["gflops"],
        },
        "per_request": {
            "wall_s": per_request_wall,
            "solves_per_sec": requests / per_request_wall,
        },
        # round 9: per-shape cost rows harvested at the AOT seam (model
        # flops, XLA bytes-accessed, arg/out/temp/peak HBM, collective
        # census) and the session's point-in-time HBM gauges
        "cost_log": sess.cost_log,
        "hbm": snap.get("gauges", {}),
        # round 12: the SLO view of the bench run (objective name ->
        # worst burn rate / breached) — CPU-smoke breaches are expected
        # and honest (cold compiles blow any ms-scale latency target)
        "slo": {
            o["name"]: {"worst_burn_rate": o["worst_burn_rate"],
                        "breached": o["breached"]}
            for o in sess.slo.evaluate()["objectives"]
        },
        # round 15: the tenant attribution view of the bench workload —
        # per-tenant counter totals, the placement snapshot (schema-
        # validated by the Session producer AND by bench_gate
        # --check-schema on the committed fixture), and the
        # conservation check: per-tenant rows sum bit-exactly to the
        # global counters (obs/attribution.py dyadic-grid invariant)
        "tenants": _tenants_section(sess),
        # round 16: the numerical-health view — per-handle condest/
        # growth/residual signals and states, probe counters, and the
        # healthy-verdict exit gate (a serving bench that cannot tell
        # its operand is healthy cannot be trusted to flag a sick one)
        "numerics": _numerics_section(sess),
        # round 18: the quota view — the declared tenant policies,
        # each tenant's live resident bytes vs its sub-budget, and the
        # quota counters (exit-gated enabled: a bench session whose
        # tenant table went missing would silently stop exercising the
        # round-18 seams)
        "quotas": sess.quotas_payload(),
        # round 19: the resident-spectral structural view — zero new
        # compiles across theta-varying serves, the two-gemm dot
        # census of every warmed apply program, the staged factor
        # programs, and a solve-with-shift accuracy check (exit-gated)
        "spectral": spectral_section,
        # round 20: the incremental-maintenance structural view — two
        # rank-k mutations served against the resident factor with
        # zero full refactors and zero new compiles after warmup,
        # plus the post-mutation solve accuracy check (exit-gated)
        "updates": updates_section,
        # round 21: the tuning-table structural view — the committed
        # TUNING_r01.json loads, a registered operator resolves its
        # config with provenance, and the warmed tuned solve path
        # compiles nothing new (exit-gated; the measured tuned-vs-
        # default A/B is the separate --tuned artifact)
        "tuning": tuning_section,
        # round 22: the black-box view — journal/counter parity per
        # decision kind and one probe incident held to
        # slate_tpu.incident.v1 (exit-gated below and by bench_gate
        # --check-schema on the committed fixture)
        "incidents": incidents_section,
        # round 23: the sensing-substrate view — the bounded
        # time-series store's full /history payload, the /forecast
        # document over it, and exact counter conservation between
        # the store's delta sums and the live metric counters
        # (exit-gated below and by bench_gate --check-schema)
        "forecast": forecast_section,
    }
    artifact["speedup"] = (artifact["serve"]["solves_per_sec"]
                           / artifact["per_request"]["solves_per_sec"])
    missing = [s for s in SERVE_ARTIFACT_SECTIONS if s not in artifact]
    assert not missing, f"serve artifact missing sections {missing}"
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")
    # exposition-format fixtures alongside the headline artifact
    # (ISSUE 4): the full Metrics snapshot as JSON and the Prometheus
    # text rendering a fleet scraper would pull from /metrics — so a
    # BENCH_SERVE run doubles as a committed example of both formats
    stem = out_path[:-5] if out_path.endswith(".json") else out_path
    sess.metrics.to_json(stem + ".metrics.json")
    from slate_tpu.obs import render_prometheus
    with open(stem + ".prom", "w") as f:
        f.write(render_prometheus(snap))
    print(f"# metrics snapshot -> {stem}.metrics.json, prometheus text "
          f"-> {stem}.prom", file=sys.stderr)
    print(json.dumps(artifact, sort_keys=True))
    return artifact


def _hlo_one_program(name: str, batch: int, n: int) -> bool:
    """Structural evidence for one row: THIS row's bucket program's
    optimized HLO carries NO per-item factorization custom call (a
    vmap of lax.linalg custom calls would — the lowering class round 7
    measured 6× slower). Filtered to the row's (pow2 batch, n) program
    so one offending shape can't taint every other row's flag."""
    import re as _re

    from slate_tpu.linalg import batched as lb

    texts = lb.bucket_hlo(name, batch=batch, n=n)
    if not texts:
        return False
    pat = _re.compile(r"custom-call.*(getrf|potrf|geqrf|lu|cholesky)",
                      _re.IGNORECASE)
    return not any(pat.search(t) for t in texts)


def bench_batched(batch_sizes=(100, 1000, 10000), sizes=(32, 64, 128, 256),
                  ops=("gesv", "posv"), dtype=np.float32,
                  per_request_cap=64, mem_cap_bytes=1 << 30,
                  out_path="BENCH_r08.json"):
    """Req/s A/B per (op, n, B): ONE batched program vs B per-request
    (B=1) programs, both through the pow2-bucket engine, both warmed
    (compilation excluded — the bucket cache makes it a one-time cost
    per (op, n, nb, dtype, pow2-B)). Writes a JSON list of
    ``serve_batched`` rows."""
    import jax

    import slate_tpu as st
    from slate_tpu.linalg import batched as lb

    platform = jax.devices()[0].platform
    rng = np.random.default_rng(23)
    rows = []
    for n in sizes:
        for bsz in batch_sizes:
            itemsize = np.dtype(dtype).itemsize
            need = lb.batch_bucket(bsz) * n * n * itemsize * 4
            if need > mem_cap_bytes:
                print(f"# skip n={n} B={bsz}: ~{need >> 20} MiB stacked "
                      f"operands over the {mem_cap_bytes >> 20} MiB cap",
                      file=sys.stderr)
                continue
            base = rng.standard_normal((bsz, n, n)).astype(dtype)
            rhs = rng.standard_normal((bsz, n, 2)).astype(dtype)
            for op in ops:
                if op == "posv":
                    a = (base @ np.swapaxes(base, 1, 2)
                         + n * np.eye(n, dtype=dtype))
                    fn = st.posv_batched
                else:
                    a = base
                    fn = st.gesv_batched
                # warm both program buckets (pow2-B and B=1)
                jax.block_until_ready(fn(a, rhs)[0])
                jax.block_until_ready(fn(a[:1], rhs[:1])[0])
                t0 = time.perf_counter()
                x, info = fn(a, rhs)
                jax.block_until_ready(x)
                batched_wall = time.perf_counter() - t0
                # per-request arm: bounded sample, same engine at B=1
                m = min(bsz, per_request_cap)
                t0 = time.perf_counter()
                for i in range(m):
                    xi, _ = fn(a[i:i + 1], rhs[i:i + 1])
                jax.block_until_ready(xi)
                per_req_wall = (time.perf_counter() - t0) * (bsz / m)
                row = {
                    "bench": "serve_batched", "platform": platform,
                    "dtype": np.dtype(dtype).name, "op": op,
                    "n": n, "batch": bsz,
                    "bucket": lb.batch_bucket(bsz),
                    "batched": {
                        "wall_s": batched_wall,
                        "reqs_per_sec": bsz / batched_wall,
                    },
                    "per_request": {
                        "wall_s": per_req_wall,
                        "reqs_per_sec": bsz / per_req_wall,
                        "sampled": m,
                    },
                    "speedup": per_req_wall / batched_wall,
                    "hlo_one_program": _hlo_one_program(
                        f"{op}_batched", lb.batch_bucket(bsz), n),
                }
                rows.append(row)
                print(f"# {op} n={n} B={bsz}: batched "
                      f"{row['batched']['reqs_per_sec']:.0f} req/s vs "
                      f"per-request "
                      f"{row['per_request']['reqs_per_sec']:.0f} req/s "
                      f"({row['speedup']:.2f}x, "
                      f"one-program={row['hlo_one_program']})",
                      file=sys.stderr)
    with open(out_path, "w") as f:
        json.dump(rows, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps({"rows": len(rows), "out": out_path,
                      "platform": platform}))
    return rows


def _mesh_session_row(op, n, nb, dtype, requests, grid, max_batch):
    """One multichip A/B row: mesh-sharded serving vs single-device
    serving of the same operator (both warmed; factor paid once per
    arm, off the timed window)."""
    import jax

    import slate_tpu as st
    from slate_tpu.runtime import Batcher, Session

    rng = np.random.default_rng(17)
    base = rng.standard_normal((n, n)).astype(dtype)
    if op == "chol":
        dense = base @ base.T + n * np.eye(n, dtype=dtype)
        operand = lambda g: st.hermitian(  # noqa: E731
            np.tril(dense), nb=nb, uplo=st.Uplo.Lower, grid=g)
        kind = "chol"
    else:
        dense = base + n * np.eye(n, dtype=dtype)
        operand = lambda g: st.from_dense(dense, nb=nb, grid=g)  # noqa: E731
        kind = "lu"
    rhs = [rng.standard_normal(n).astype(dtype) for _ in range(requests)]

    def run_arm(mesh):
        sess = Session(mesh=mesh)
        h = sess.register(operand(None), op=kind)
        sess.warmup(h)
        batcher = Batcher(sess, max_batch=max_batch, max_wait=60.0,
                          pad_widths=True)
        # prime every pow2 width program off the timed window (the
        # compile cost is a one-time warmup cost, not serving cost)
        w = 1
        while w <= max_batch:
            futs = [batcher.submit(h, b) for b in rhs[:w]]
            batcher.flush()
            [f.result() for f in futs]
            w <<= 1
        t0 = time.perf_counter()
        futs = [batcher.submit(h, b) for b in rhs]
        for _ in range((requests + max_batch - 1) // max_batch):
            batcher.flush()
        xs = [f.result() for f in futs]
        wall = time.perf_counter() - t0
        return sess, h, xs, wall

    mesh_sess, mh, mesh_xs, mesh_wall = run_arm(grid)
    single_sess, sh, single_xs, single_wall = run_arm(None)

    # correctness: both arms agree with each other and with A·x = b
    max_diff = max(float(np.abs(a - b).max())
                   for a, b in zip(mesh_xs, single_xs))
    resid = max(float(np.abs(dense @ x - b).max())
                for x, b in zip(mesh_xs[:4], rhs[:4])) / n
    # dtype-aware bounds on BOTH guards: an f64 arm held only to the
    # f32 threshold would let a genuinely-wrong sharded solve ship an
    # ok=true artifact
    tol = 1e-2 if np.dtype(dtype).itemsize == 4 else 1e-8
    if not (resid < tol and max_diff < tol * n):
        raise RuntimeError(
            f"multichip {op} n={n}: arms disagree (diff={max_diff}, "
            f"resid={resid})")

    res = mesh_sess.factor(mh)
    leaf = res.payload[0]
    sharding = getattr(getattr(leaf, "data", leaf), "sharding", None)
    sharded = bool(sharding is not None
                   and not sharding.is_fully_replicated)
    solve_rows = [r for r in mesh_sess.cost_log if r["what"] == "solve"]
    census = {}
    census_bytes = 0
    for r in solve_rows:
        for k, v in r["collectives"].items():
            census[k] = census.get(k, 0) + v["count"]
        census_bytes += r["collective_bytes"]
    snap = mesh_sess.metrics.snapshot()["counters"]
    solves = snap.get("solves_total", 0) or 1
    return {
        "op": op, "n": n, "nb": nb,
        "dtype": np.dtype(dtype).name, "requests": requests,
        "serve": {"wall_s": mesh_wall,
                  "solves_per_sec": requests / mesh_wall},
        "single_device": {"wall_s": single_wall,
                          "solves_per_sec": requests / single_wall},
        "speedup": single_wall / mesh_wall,
        "max_abs_diff_vs_single_device": max_diff,
        "sharded_resident": sharded,
        "resident_bytes_per_chip": res.nbytes,
        "resident_bytes_total": res.nbytes_total,
        # scheduled-HLO structural evidence: the served solve
        # program(s) contain real collectives, and serving credited
        # measured ICI bytes per executed solve
        "solve_collective_census": census,
        "solve_collective_bytes_per_program": census_bytes,
        "collective_bytes_per_solve":
            snap.get("solve_collective_bytes_total", 0.0) / solves,
        "one_program_per_shape": True,  # overwritten below by caller
        "aot_solve_compiles": snap.get("aot_compiles", 0),
    }


def bench_multichip(n=128, nb=32, requests=32, max_batch=8,
                    dtypes=("float32", "float64"), n_devices=8,
                    mesh_shape=None, out_path="MULTICHIP_r06.json"):
    """The pod-scale serving artifact (module docstring). Requires
    ``n_devices`` devices to be visible (main() forces a virtual
    host-platform mesh in a child process when they are not);
    ``mesh_shape`` defaults to the near-square p×q factorization of
    ``n_devices`` (the BLACS default-grid rule, core/grid.py)."""
    import jax
    from slate_tpu.core.grid import ProcessGrid, _near_square_factor

    if mesh_shape is None:
        p = _near_square_factor(n_devices)
        mesh_shape = (p, n_devices // p)
    p, q = mesh_shape
    if len(jax.devices()) < p * q:
        raise RuntimeError(
            f"bench_multichip: need {p * q} devices, have "
            f"{len(jax.devices())} (run via --multichip, which forces "
            "a virtual host mesh)")
    grid = ProcessGrid.create(p, q)
    platform = jax.devices()[0].platform
    if platform != "cpu":
        # TPU v5 has no f64 datapath (and no x64 downcast honesty
        # either) — f32 rows only on real accelerators
        dtypes = tuple(d for d in dtypes if np.dtype(d).itemsize == 4)
    import jax.numpy as _jnp  # noqa: F401
    if platform == "cpu" and not jax.config.jax_enable_x64:
        dtypes = tuple(d for d in dtypes
                       if np.dtype(d).itemsize == 4)
        print("# x64 disabled: dropping float64 rows (a downcast f64 "
              "arm would be dishonest)", file=sys.stderr)
    rows = []
    for dtype_name in dtypes:
        dtype = np.dtype(dtype_name).type
        for op in ("chol", "lu"):
            row = _mesh_session_row(op, n, nb, dtype, requests, grid,
                                    max_batch)
            # one sharded program per (op, shape, dtype, mesh): the
            # timed window added no solve compiles beyond the pow2
            # width set primed during warmup (log2(max_batch)+1 widths
            # + the nrhs=1 warmup shape)
            import math
            expected = int(math.log2(max_batch)) + 2
            row["one_program_per_shape"] = (
                row["aot_solve_compiles"] <= expected)
            ok = (row["sharded_resident"]
                  and row["one_program_per_shape"]
                  and row["solve_collective_bytes_per_program"] > 0)
            row["ok"] = ok
            rows.append(row)
            print(f"# multichip {op} n={n} {dtype_name}: mesh "
                  f"{row['serve']['solves_per_sec']:.1f} solves/s vs "
                  f"single {row['single_device']['solves_per_sec']:.1f}"
                  f" ({row['speedup']:.2f}x), sharded="
                  f"{row['sharded_resident']}, census="
                  f"{row['solve_collective_census']}", file=sys.stderr)
    artifact = {
        "bench": "multichip",
        "platform": platform,
        "forced_host_devices": platform == "cpu",
        "mesh_shape": list(mesh_shape),
        "n_devices": p * q,
        "caveat": ("CPU-forced virtual mesh smoke (TPU tunnel down "
                   "since round 5): wall-clock columns are "
                   "dispatch-bound and informational; the sharded-"
                   "resident, census, and one-program columns are the "
                   "structural claim." if platform == "cpu" else None),
        "rows": rows,
        "ok": all(r["ok"] for r in rows),
    }
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps({"rows": len(rows), "out": out_path,
                      "platform": platform,
                      "ok": artifact["ok"]}))
    return artifact


def bench_mixed(sizes=(128, 256), nb=32, requests=32,
                dtype=np.float32, factor_dtype="bfloat16",
                budget_residents=3, out_path="BENCH_MIXED_r01.json"):
    """The mixed-precision serving A/B (round 13, ISSUE 10): a Session
    holding a LOW-precision resident factor + iterative-refinement
    solves (``register(..., refine=...)`` through slate_tpu/refine/)
    vs the same operator served at full precision. Per (op, n) row:
    both arms' solves/sec (warmed; factor paid off the timed window),
    the refined arm's mean iteration count, each arm's RESIDENT FACTOR
    BYTES (the structural claim: a bf16-from-f32 resident charges ~half
    — pinned by the ``factor_bytes_ratio`` column), and a
    residents-per-budget experiment: a budget sized for
    ``budget_residents`` full-precision factors (plus the arm's own
    analyzed-program transient, which the round-9 budget also charges)
    is filled with 2·N+1 distinct operators — the mixed arm holds ~2×
    as many residents before eviction (``residents_ratio``).

    CPU-smoke honesty: wall-clock columns on this host are
    informational — XLA:CPU materializes f32↔bf16 converts around
    every gemm, so refined serving can read SLOWER; the structural
    columns (factor bytes, residents, iters) are the portable claim
    and the TPU series gate on solves/sec when the tunnel returns."""
    import jax

    import slate_tpu as st
    from slate_tpu.refine import RefinePolicy
    from slate_tpu.runtime import Session

    platform = jax.devices()[0].platform
    rng = np.random.default_rng(29)
    rows = []
    for op in ("chol", "lu"):
        for n in sizes:
            base = rng.standard_normal((n, n)).astype(dtype)
            if op == "chol":
                dense = base @ base.T + n * np.eye(n, dtype=dtype)

                def operand(shift=0.0):
                    return st.hermitian(
                        np.tril(dense) + shift * np.eye(n, dtype=dtype),
                        nb=nb, uplo=st.Uplo.Lower)
            else:
                dense = base + n * np.eye(n, dtype=dtype)

                def operand(shift=0.0):
                    return st.from_dense(
                        dense + shift * np.eye(n, dtype=dtype), nb=nb)
            rhs = [rng.standard_normal(n).astype(dtype)
                   for _ in range(requests)]

            def run_arm(policy):
                sess = Session()
                h = sess.register(operand(), op=op, refine=policy)
                sess.warmup(h)
                sess.solve(h, rhs[0])  # warm every program (incl. step)
                t0 = time.perf_counter()
                for b in rhs:
                    x = sess.solve(h, b)
                wall = time.perf_counter() - t0
                return sess, h, x, wall

            pol = RefinePolicy(factor_dtype=factor_dtype)
            ms, mh, mx, mwall = run_arm(pol)
            fs, fh, fx, fwall = run_arm(None)
            # correctness: refined serving must meet the same bound
            # the full-precision arm does (a fast wrong answer is not
            # a win)
            for x in (mx, fx):
                resid = float(np.abs(dense @ x - rhs[-1]).max()) / n
                if not resid < 1e-2:
                    raise RuntimeError(
                        f"mixed bench {op} n={n}: residual {resid}")
            mixed_bytes = ms.factor(mh).nbytes
            full_bytes = fs.factor(fh).nbytes
            hist = ms.metrics.snapshot()["histograms"].get(
                "refine_iterations", {})

            def residents(policy, probe_sess):
                # budget sized for `budget_residents` FULL-precision
                # factors + this arm's largest analyzed-program
                # transient (the round-9 budget charges it too; the
                # plain arm below runs unanalyzed programs, transient 0)
                transient = max(
                    (pc.transient_bytes
                     for pc in probe_sess._program_costs.values()),
                    default=0)
                sess = Session(hbm_budget=budget_residents * full_bytes
                               + transient)
                hs = [sess.register(operand((i + 1) * 0.5), op=op,
                                    refine=policy)
                      for i in range(2 * budget_residents + 1)]
                for h in hs:
                    sess.solve(h, rhs[0])
                return len(sess.cached_handles())

            res_m = residents(pol, ms)
            res_f = residents(None, Session())  # plain arm: no analyzed
            row = {
                "op": op, "n": n, "nb": nb, "requests": requests,
                "dtype": np.dtype(dtype).name,
                "factor_dtype": factor_dtype,
                "mixed": {
                    "wall_s": mwall,
                    "solves_per_sec": requests / mwall,
                    "iters_mean": hist.get("mean") or 0.0,
                    "factor_bytes": mixed_bytes,
                    "residents_within_budget": res_m,
                },
                "full": {
                    "wall_s": fwall,
                    "solves_per_sec": requests / fwall,
                    "factor_bytes": full_bytes,
                    "residents_within_budget": res_f,
                },
                "speedup": fwall / mwall,
                "factor_bytes_ratio": mixed_bytes / full_bytes,
                "residents_ratio": res_m / max(res_f, 1),
                "refine_fallbacks": ms.metrics.get(
                    "refine_fallbacks_total"),
            }
            # structural acceptance: half-bytes residents, ≥ ~2× of
            # them per budget, and every timed solve actually refined
            # (zero fallbacks on these well-conditioned operators)
            row["ok"] = (row["factor_bytes_ratio"] < 0.6
                         and res_f == budget_residents
                         and res_m >= 2 * budget_residents - 1
                         and row["refine_fallbacks"] == 0)
            rows.append(row)
            print(f"# mixed {op} n={n}: refined "
                  f"{row['mixed']['solves_per_sec']:.1f} solves/s vs "
                  f"full {row['full']['solves_per_sec']:.1f} "
                  f"({row['speedup']:.2f}x), bytes ratio "
                  f"{row['factor_bytes_ratio']:.2f}, residents "
                  f"{res_m} vs {res_f}, iters "
                  f"{row['mixed']['iters_mean']:.1f}", file=sys.stderr)
    artifact = {
        "bench": "serve_mixed",
        "platform": platform,
        "dtype": np.dtype(dtype).name,
        "factor_dtype": factor_dtype,
        "caveat": ("CPU smoke (TPU tunnel down since round 5): "
                   "wall-clock columns are informational — XLA:CPU "
                   "materializes f32<->bf16 converts around every "
                   "gemm; the factor-bytes / residents-per-budget / "
                   "iteration columns are the structural claim."
                   if platform == "cpu" else None),
        "rows": rows,
        "ok": all(r["ok"] for r in rows),
    }
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps({"rows": len(rows), "out": out_path,
                      "platform": platform, "ok": artifact["ok"]}))
    return artifact


def bench_overload(n=64, nb=32, service_ms=5.0, duration_s=1.5,
                   overload=2.0, max_age_s=0.05, seed=1,
                   out_path="BENCH_OVERLOAD_r01.json"):
    """The round-14 shedding A/B: the SAME 2× sustained overload served
    with and without admission control + load shedding.

    Service time is pinned by an injected ``slow_device`` fault
    (rate 1.0, ``service_ms`` per dispatch — the fault layer doubling
    as a deterministic load model), ``max_batch=1`` so the service
    rate is 1/service_ms, and requests arrive at ``overload×`` that
    rate. The no-shed arm's queue — hence its completed-request p99
    and ``oldest_request_age_s`` — grows for as long as the overload
    lasts; the shed arm turns excess away at the door
    (``max_queue_depth``) and sheds cheapest-first past ``max_age_s``,
    so its p99 stays bounded near the age threshold. Wall-clock
    numbers on CPU are honest smoke (PERF.md policy): the CLAIM is the
    shape — bounded vs unbounded — which is dispatch-rate-independent.
    """
    import jax

    import slate_tpu as st
    from slate_tpu.runtime import (Executor, FaultPlan, FaultSpec,
                                   Session, ShedPolicy)

    platform = jax.devices()[0].platform
    service_s = service_ms * 1e-3
    interval = service_s / overload
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((n, n)).astype(np.float32)
    spd = (a @ a.T + n * np.eye(n)).astype(np.float32)

    def run_arm(shed_policy):
        sess = Session()
        sess.enable_faults(FaultPlan(seed=seed, specs=(
            FaultSpec("slow_device", rate=1.0, latency_s=service_s),)))
        h = sess.register(st.hermitian(np.tril(spd), nb=nb,
                                       uplo=st.Uplo.Lower), op="chol")
        sess.warmup(h)
        age_series = []
        futs = []
        head = 0  # first possibly-unserved future (monotone scan)
        t0 = time.perf_counter()
        with Executor(sess, max_batch=1, max_wait=1e-4, retries=0,
                      shed_policy=shed_policy) as ex:
            next_sample = 0.0
            while (now := time.perf_counter() - t0) < duration_s:
                futs.append((time.perf_counter(), ex.submit(
                    h, rng.standard_normal(n).astype(np.float32))))
                if now >= next_sample:
                    # the client-visible backlog signal: age of the
                    # oldest UNSERVED request (queued OR detached-but-
                    # undispatched — the /metrics gauge only sees the
                    # queued share)
                    while head < len(futs) and futs[head][1].done():
                        head += 1
                    age_series.append(round(
                        time.perf_counter() - futs[head][0], 4)
                        if head < len(futs) else 0.0)
                    next_sample = now + 0.1
                time.sleep(interval)
            ex.flush()
        wall = time.perf_counter() - t0
        futs = [f for _, f in futs]
        snap = sess.metrics.snapshot()
        lat = snap["histograms"].get("request_latency", {})
        g = snap["counters"].get
        return {
            "submitted": len(futs),
            "completed": g("completed_requests", 0.0),
            "shed": g("shed_requests_total", 0.0),
            "admission_rejected": g("admission_rejected_total", 0.0),
            "load_sheds": g("load_sheds_total", 0.0),
            "p50_latency_s": lat.get("p50", 0.0),
            "p99_latency_s": lat.get("p99", 0.0),
            "oldest_age_series_s": age_series,
            "max_oldest_age_s": max(age_series, default=0.0),
            "wall_s": wall,
        }

    shed = run_arm(ShedPolicy(max_queue_depth=16, max_age_s=max_age_s,
                              shed_fraction=0.5, min_queue_depth=4))
    no_shed = run_arm(None)
    # the claim: shedding BOUNDS the completed-request p99 and the
    # queue age; without it both grow with the overload duration
    series = no_shed["oldest_age_series_s"]
    half = len(series) // 2 or 1
    no_shed_grows = (len(series) >= 2
                     and series[-1] > 1.5 * max(max(series[:half]), 1e-6)
                     and no_shed["max_oldest_age_s"] > 2 * max_age_s)
    ok = (shed["p99_latency_s"] < no_shed["p99_latency_s"] / 2
          and shed["max_oldest_age_s"] < no_shed["max_oldest_age_s"] / 2
          and (shed["shed"] > 0 or shed["admission_rejected"] > 0)
          and no_shed_grows)
    artifact = {
        "bench": "serve_overload",
        "platform": platform,
        "n": n, "nb": nb,
        "service_ms": service_ms,
        "overload_factor": overload,
        "duration_s": duration_s,
        "max_age_s": max_age_s,
        "arms": {"shed": shed, "no_shed": no_shed},
        "no_shed_age_grows": no_shed_grows,
        "caveat": ("CPU smoke (TPU tunnel down since round 5): service "
                   "time is an injected slow-device fault, so the "
                   "latency scale is synthetic; the bounded-vs-"
                   "unbounded SHAPE under 2x overload is the claim."
                   if platform == "cpu" else None),
        "ok": ok,
    }
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"# overload 2x: shed p99 {shed['p99_latency_s']*1e3:.1f} ms "
          f"(max age {shed['max_oldest_age_s']*1e3:.0f} ms, "
          f"shed {shed['shed']:.0f} + rejected "
          f"{shed['admission_rejected']:.0f}) vs no-shed p99 "
          f"{no_shed['p99_latency_s']*1e3:.1f} ms (max age "
          f"{no_shed['max_oldest_age_s']*1e3:.0f} ms, growing="
          f"{no_shed_grows})", file=sys.stderr)
    print(json.dumps({"out": out_path, "ok": ok,
                      "shed_p99_ms": shed["p99_latency_s"] * 1e3,
                      "no_shed_p99_ms": no_shed["p99_latency_s"] * 1e3}))
    return artifact


def bench_tenants_fair(n=48, nb=16, service_ms=10.0, waves=4,
                       max_batch=4, seed=1,
                       out_path="BENCH_FAIR_r01.json"):
    """The round-18 tenant-isolation A/B: the SAME 2× sustained
    overload — an aggressor tenant arriving at 3× the victim's rate —
    served FIFO with no quotas (the pre-round-18 runtime) vs with
    weighted-fair dispatch + tenant quotas ON.

    Service time is pinned by an injected ``slow_device`` fault (the
    bench_overload recipe: the fault layer doubling as a deterministic
    load model) and the workload is WAVE-LOCKED on the caller's thread
    (the chaos_serve determinism discipline — each wave submits the
    aggressor's 2×-overload backlog plus the victim's modest share,
    then pumps the Batcher one bucket at a time): the latency story is
    dispatch ORDER times the pinned service time, not host scheduler
    noise. Requests carry explicit ``tenant=`` labels so tenant
    buckets never coalesce (the round-15 key split). In the FAIR arm
    the victim (weight 4, arriving under its share) keeps a bounded
    p99 — its buckets dispatch within the DRR starvation bound — and
    the aggressor's excess is quota-rejected at its in-flight cap,
    counted per tenant. In the FIFO arm the same seed starves the
    victim: its p99 tracks the aggressor's whole backlog. Both arms:
    zero lost futures (every future resolves — completed or
    counted-rejected), zero wrong answers. Wall-clock numbers on CPU
    are honest smoke (PERF.md policy): the CLAIM is the shape —
    bounded vs starved victim p99 under the same overload — which is
    dispatch-rate-independent."""
    import jax

    import slate_tpu as st
    from slate_tpu.runtime import (Batcher, FaultPlan, FaultSpec,
                                   QuotaExceeded, Session, TenantPolicy)

    platform = jax.devices()[0].platform
    service_s = service_ms * 1e-3
    rng0 = np.random.default_rng(seed)
    a = rng0.standard_normal((n, n)).astype(np.float32)
    spd = (a @ a.T + n * np.eye(n)).astype(np.float32)
    agg_per_wave, victim_per_wave = 10 * max_batch, max_batch

    def run_arm(fair):
        policies = None
        if fair:
            policies = {
                "victim": TenantPolicy(weight=4.0),
                "aggressor": TenantPolicy(weight=1.0,
                                          max_in_flight=4 * max_batch),
            }
        rng = np.random.default_rng(seed + 1)
        sess = Session(tenant_policies=policies)
        sess.enable_attribution()
        sess.enable_faults(FaultPlan(seed=seed, specs=(
            FaultSpec("slow_device", rate=1.0, latency_s=service_s),)))
        h = sess.register(st.hermitian(np.tril(spd), nb=nb,
                                       uplo=st.Uplo.Lower), op="chol",
                          tenant="victim")
        sess.warmup(h)
        bat = Batcher(sess, max_batch=max_batch, max_wait=3600.0)
        stats = {t: {"submitted": 0, "lat": [], "rejected": 0}
                 for t in ("victim", "aggressor")}
        wrong = lost = 0
        t_start = time.perf_counter()
        for wave in range(waves + 1):
            recorded = wave > 0  # wave 0 pays the one-time compiles
            futs = []
            for _ in range(agg_per_wave):
                b = rng.standard_normal(n).astype(np.float32)
                stats["aggressor"]["submitted"] += recorded
                futs.append(("aggressor",
                             bat.submit(h, b, tenant="aggressor"), b))
            for _ in range(victim_per_wave):
                b = rng.standard_normal(n).astype(np.float32)
                stats["victim"]["submitted"] += recorded
                futs.append(("victim",
                             bat.submit(h, b, tenant="victim"), b))
            t0 = time.perf_counter()
            done_at = {}
            for key, reqs in bat.pop_ready(force=True):
                bat.run(key, reqs)
                now = time.perf_counter() - t0
                for r in reqs:
                    done_at[id(r.future)] = now
            for tenant, f, b in futs:
                if not f.done():
                    lost += 1
                    continue
                err = f.exception()
                if err is not None:
                    if isinstance(err, QuotaExceeded):
                        stats[tenant]["rejected"] += recorded
                    else:
                        lost += 1
                    continue
                if recorded:
                    stats[tenant]["lat"].append(done_at.get(id(f), 0.0))
                x = f.result()
                if float(np.abs(spd.astype(np.float64)
                                @ np.asarray(x, np.float64)
                                - b).max()) \
                        / (n * max(float(np.abs(x).max()), 1.0)) > 1e-3:
                    wrong += 1
        wall = time.perf_counter() - t_start
        g = sess.metrics.snapshot()["counters"].get

        def p99(xs):
            return (sorted(xs)[max(int(0.99 * len(xs)) - 1, 0)]
                    if xs else 0.0)

        tenants = {}
        for t, s in stats.items():
            tenants[t] = {
                "submitted": s["submitted"],
                "completed": len(s["lat"]),
                "quota_rejected": s["rejected"],
                "reqs_per_sec": (len(s["lat"]) / wall
                                 if wall > 0 else 0.0),
                "p50_latency_s": (sorted(s["lat"])[len(s["lat"]) // 2]
                                  if s["lat"] else 0.0),
                "p99_latency_s": p99(s["lat"]),
            }
        return {
            "wall_s": wall,
            "waves": waves,
            "tenants": tenants,
            "quota_rejections_total": g("quota_rejections_total", 0.0),
            "wrong_answers": wrong,
            "lost_futures": lost,
        }

    fair = run_arm(True)
    fifo = run_arm(False)
    v_fair, v_fifo = fair["tenants"]["victim"], fifo["tenants"]["victim"]
    ok = (fair["wrong_answers"] == 0 and fifo["wrong_answers"] == 0
          and fair["lost_futures"] == 0 and fifo["lost_futures"] == 0
          # the victim arrives under its share: with isolation ON it
          # completes everything it asked for with a bounded p99;
          # the SAME overload FIFO starves it
          and v_fair["completed"] >= 0.8 * v_fair["submitted"]
          and v_fair["p99_latency_s"] < v_fifo["p99_latency_s"] / 2
          # the aggressor pays for its own overload: counted quota
          # rejections ON, none OFF
          and fair["tenants"]["aggressor"]["quota_rejected"] > 0
          and fifo["tenants"]["aggressor"]["quota_rejected"] == 0)
    artifact = {
        "bench": "serve_fair",
        "platform": platform,
        "n": n, "nb": nb,
        "service_ms": service_ms,
        "waves": waves,
        "max_batch": max_batch,
        "arms": {"fair": fair, "fifo": fifo},
        "victim_p99_ratio_fifo_over_fair": (
            v_fifo["p99_latency_s"] / v_fair["p99_latency_s"]
            if v_fair["p99_latency_s"] > 0 else None),
        "caveat": ("CPU smoke (TPU tunnel down since round 5): service "
                   "time is an injected slow-device fault, so the "
                   "latency scale is synthetic; the bounded-vs-starved "
                   "victim-p99 SHAPE under the same 2x overload is the "
                   "claim." if platform == "cpu" else None),
        "ok": ok,
    }
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"# tenants-fair 2x overload: victim p99 "
          f"{v_fair['p99_latency_s']*1e3:.1f} ms fair vs "
          f"{v_fifo['p99_latency_s']*1e3:.1f} ms fifo; aggressor "
          f"rejected {fair['tenants']['aggressor']['quota_rejected']}"
          f" (fair) vs {fifo['tenants']['aggressor']['quota_rejected']}"
          f" (fifo)", file=sys.stderr)
    print(json.dumps({"out": out_path, "ok": ok,
                      "victim_p99_ms_fair":
                          v_fair["p99_latency_s"] * 1e3,
                      "victim_p99_ms_fifo":
                          v_fifo["p99_latency_s"] * 1e3}))
    return artifact


def bench_failover(n=48, nb=16, n_handles=6, seed=1,
                   out_path="BENCH_FAILOVER_r01.json"):
    """The round-17 failover A/B: the SAME member death recovered with
    replication+checkpoint vs cold refactor-on-miss.

    Both arms run a 3-member Fleet serving ``n_handles`` resident
    Cholesky operators, kill member p0, and measure recovery: wall
    time of the failover reflex, per-affected-handle time-to-first-
    successful-solve, post-crash refactor count on the survivors, and
    availability over a fixed post-crash request window. The
    PROTECTED arm replicates the two hottest handles (heat-driven,
    the round-15 placement rows) and flushes checkpoints before the
    crash, so its affected handles serve from replicas or warm
    restores with (near-)zero refactors; the COLD arm re-registers
    from the retained specs and pays one refactor per affected handle
    on first touch. Wall-clock numbers on CPU are honest smoke
    (PERF.md policy); the CLAIM is structural — the refactor-count and
    recovery-path columns, which are dispatch-rate-independent."""
    import jax

    import slate_tpu as st
    from slate_tpu.runtime import Fleet, Session, ShedPolicy

    platform = jax.devices()[0].platform
    rng = np.random.default_rng(seed)
    mats = []
    for i in range(n_handles):
        a = rng.standard_normal((n, n)).astype(np.float32)
        mats.append((a @ a.T + n * np.eye(n)).astype(np.float32))
    rhs = [rng.standard_normal(n).astype(np.float32)
           for _ in range(n_handles * 8)]

    def run_arm(protected):
        import shutil
        import tempfile
        root = tempfile.mkdtemp(prefix="slate_failover_")
        sessions = {
            f"p{i}": Session(
                hbm_budget=256 << 20,
                checkpoint_dir=(os.path.join(root, f"p{i}")
                                if protected else None))
            for i in range(3)}
        fleet = Fleet(sessions, max_batch=8, max_wait=3600.0,
                      checkpoint_root=root if protected else None,
                      shed_policy=ShedPolicy(max_queue_depth=256,
                                             min_queue_depth=2))
        for s in sessions.values():
            s.enable_attribution()
        handles = []
        for i, m in enumerate(mats):
            h = fleet.register(
                st.hermitian(np.tril(m), nb=nb, uplo=st.Uplo.Lower),
                op="chol", handle=f"h{i}", member=f"p{i % 3}")
            handles.append(h)
        fleet.warmup()
        # warm traffic (builds heat; victim-hosted handles hottest so
        # replicate_hot protects exactly what the crash will take)
        victim = "p0"
        affected = [h for h in handles
                    if fleet.placement_of(h) == [victim]]
        for rounds, hs in ((2, handles), (3, affected)):
            for _ in range(rounds):
                futs = [fleet.submit(h, rhs[i % len(rhs)])
                        for i, h in enumerate(hs)]
                fleet.flush()
                assert all(f.exception() is None for f in futs)
        if protected:
            fleet.replicate_hot(2)
            fleet.checkpoint_all()
        survivors = [m for m in fleet.alive() if m != victim]
        pre_factors = sum(fleet.member(m).metrics.get("factors_total")
                          for m in survivors)
        t0 = time.perf_counter()
        fleet.kill(victim)
        failover_s = time.perf_counter() - t0
        # per-handle recovery: time to the first successful solve of
        # each affected handle after the death was declared
        recovery_s = {}
        wrong = 0
        for h in affected:
            t1 = time.perf_counter()
            f = fleet.submit(h, rhs[0])
            fleet.flush()
            recovery_s[h] = time.perf_counter() - t1
            x = f.result()
            m = mats[handles.index(h)]
            resid = float(np.abs(
                m.astype(np.float64) @ np.asarray(x, np.float64)
                - rhs[0]).max()) / (n * max(float(np.abs(x).max()), 1.0))
            if resid > 1e-3:
                wrong += 1
        # availability window: a fixed post-crash request batch
        futs = [fleet.submit(h, rhs[(i + 1) % len(rhs)])
                for _ in range(4) for i, h in enumerate(handles)]
        fleet.flush()
        done_ok = sum(1 for f in futs
                      if f.done() and f.exception() is None)
        refactors = sum(fleet.member(m).metrics.get("factors_total")
                        for m in survivors) - pre_factors
        g = fleet.metrics.get
        shutil.rmtree(root, ignore_errors=True)
        return {
            "affected_handles": len(affected),
            "failover_s": failover_s,
            "recovery_s_max": max(recovery_s.values(), default=0.0),
            "recovery_s_mean": (sum(recovery_s.values())
                                / max(len(recovery_s), 1)),
            "refactors_after_crash": refactors,
            "replica_served": g("fleet_failover_replica_served"),
            "restored": g("fleet_failover_restored"),
            "cold_registered": g("fleet_failover_cold"),
            "availability": done_ok / max(len(futs), 1),
            "completed": done_ok,
            "wrong_answers": wrong,
        }

    protected = run_arm(True)
    cold = run_arm(False)
    # the structural claim: replication+checkpoint recovers WARM —
    # every affected handle serves from a replica or a restored
    # resident with zero refactors, while the cold arm refactors each
    # one on first touch (CPU wall times are informational smoke)
    ok = (protected["wrong_answers"] == 0 and cold["wrong_answers"] == 0
          and protected["refactors_after_crash"] == 0
          and cold["refactors_after_crash"] >= cold["affected_handles"]
          and protected["replica_served"] + protected["restored"]
          >= protected["affected_handles"]
          and cold["cold_registered"] >= cold["affected_handles"]
          and protected["availability"] == 1.0
          and cold["availability"] == 1.0)
    artifact = {
        "bench": "serve_failover",
        "platform": platform,
        "n": n, "nb": nb, "handles": n_handles,
        "members": 3,
        "arms": {"protected": protected, "cold": cold},
        "recovery_speedup": (cold["recovery_s_max"]
                             / protected["recovery_s_max"]
                             if protected["recovery_s_max"] > 0
                             else None),
        "caveat": ("CPU smoke (TPU tunnel down since round 5): "
                   "recovery wall times are host-dispatch-bound; the "
                   "structural claim is the refactor-count and "
                   "recovery-path columns (replica/restored vs cold), "
                   "which are dispatch-rate-independent."
                   if platform == "cpu" else None),
        "ok": ok,
    }
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"# failover: protected recovered {protected['affected_handles']}"
          f" handles with {protected['refactors_after_crash']:.0f} "
          f"refactors (max {protected['recovery_s_max']*1e3:.1f} ms) vs "
          f"cold {cold['refactors_after_crash']:.0f} refactors "
          f"(max {cold['recovery_s_max']*1e3:.1f} ms)", file=sys.stderr)
    print(json.dumps({"out": out_path, "ok": ok,
                      "protected_refactors":
                          protected["refactors_after_crash"],
                      "cold_refactors": cold["refactors_after_crash"]}))
    return artifact


def bench_spectral(n=96, nb=32, requests=32, cold_sample=6,
                   out_path="BENCH_SPECTRAL_r01.json"):
    """The round-19 resident-spectral A/B: serve ``requests``
    theta-varying matrix-function applies from a RESIDENT
    eigendecomposition (two analyzed gemms + a diagonal scale per
    request, zero compiles after warmup) vs re-running the full
    two-stage decomposition per request (api.heev_mesh / svd_mesh —
    what a caller without a resident spectral pays) and applying
    eagerly.

    One row per op (eig, svd). The cold arm is measured on a bounded
    sample (``cold_sample`` — a 9n³ decomposition per request makes a
    full sweep pointless) and extrapolated to a rate. CPU wall times
    are honest smoke (PERF.md policy); the structural columns — zero
    new compiles across the serve sweep, the two-gemm dot census of
    every warmed apply program, the staged factor programs' census
    rows — are the portable claim."""
    import jax

    import slate_tpu as st
    from slate_tpu import spectral as sp
    from slate_tpu.runtime import Session

    platform = jax.devices()[0].platform
    rng = np.random.default_rng(19)
    rows = []
    for op in ("eig", "svd"):
        if op == "eig":
            m = n
            a = rng.standard_normal((n, n)).astype(np.float32)
            a = ((a + a.T) / 2).astype(np.float32)
            A = st.from_dense(a, nb=nb, kind=st.MatrixKind.Hermitian)
            dense = a
        else:
            m = n + nb
            dense = rng.standard_normal((m, n)).astype(np.float32)
            A = st.from_dense(dense, nb=nb)
        # solve rhs rows: n for eig, m for svd (pinv direction)
        rhs = [rng.standard_normal(n if op == "eig" else m)
               .astype(np.float32) for _ in range(requests)]

        sess = Session(hbm_budget=1 << 30)
        h = sess.register(A, op=op)
        sess.warmup(h, nrhs=1)
        nc0 = len(sess.compile_log)
        shift = 0.3
        t0 = time.perf_counter()
        for i, b in enumerate(rhs):
            x = sess.apply(h, b, fn="solve",
                           theta=shift * ((i % 4) + 1))
        warm_wall = time.perf_counter() - t0
        new_compiles = len(sess.compile_log) - nc0
        dots = _apply_dot_census(sess)

        # accuracy spot check on the last served theta
        theta = shift * (((requests - 1) % 4) + 1)
        a64 = dense.astype(np.float64)
        if op == "eig":
            xd = np.linalg.solve(a64 - theta * np.eye(n), rhs[-1])
        else:
            # Tikhonov-regularized pinv: sigma/(sigma^2+theta^2)
            u, s, vt = np.linalg.svd(a64, full_matrices=False)
            w = s / (s * s + theta * theta)
            xd = vt.T @ (w * (u.T @ rhs[-1]))
        rel = float(np.abs(np.asarray(x, np.float64) - xd).max()
                    / max(np.abs(xd).max(), 1.0))

        # cold arm: the full two-stage decomposition per request (the
        # mesh api verbs), eager apply — bounded sample, extrapolated
        ncold = min(requests, cold_sample)
        decomp = (st.api.heev_mesh if op == "eig"
                  else st.api.svd_mesh)
        decomp(A)  # warm the staged compile caches off the clock
        t0 = time.perf_counter()
        for i in range(ncold):
            th = shift * ((i % 4) + 1)
            if op == "eig":
                w, Z = decomp(A)
                V = Z.to_numpy()
                xc = V @ ((V.T @ rhs[i]) / (np.asarray(w) - th))
            else:
                s_, U, V = decomp(A)
                s_ = np.asarray(s_)
                wv = s_ / (s_ * s_ + th * th)
                xc = V.to_numpy() @ (wv * (U.to_numpy().T @ rhs[i]))
        cold_wall = time.perf_counter() - t0
        census = [{k: r.get(k) for k in
                   ("what", "model_flops", "bytes_accessed",
                    "collective_bytes")}
                  for r in sess.cost_log
                  if r["what"].startswith("spectral.")]
        row = {
            "op": op, "m": m, "n": n, "nb": nb,
            "functions": sorted(sp.function_catalog(op)),
            "resident": {"wall_s": warm_wall,
                         "applies_per_sec": requests / warm_wall},
            "cold": {"wall_s": cold_wall, "sampled": ncold,
                     "applies_per_sec": ncold / cold_wall},
            "speedup": (requests / warm_wall) / (ncold / cold_wall),
            "new_compiles_after_warmup": new_compiles,
            "apply_dot_ops": dots,
            "census": census,
            "max_rel_err": rel,
        }
        row["one_program"] = (new_compiles == 0 and bool(dots)
                              and all(v == 2 for v in dots.values()))
        rows.append(row)
        print(f"# spectral[{op}]: resident "
              f"{row['resident']['applies_per_sec']:.1f} applies/s vs "
              f"cold {row['cold']['applies_per_sec']:.1f} "
              f"decomp+apply/s -> {row['speedup']:.1f}x "
              f"(compiles after warmup: {new_compiles})",
              file=sys.stderr)

    ok = all(r["one_program"] and r["max_rel_err"] < 1e-3
             and r["speedup"] > 1.0 for r in rows)
    artifact = {
        "bench": "serve_spectral",
        "platform": platform,
        "n": n, "nb": nb, "requests": requests,
        "rows": rows,
        "caveat": ("CPU smoke (TPU tunnel down since round 5): "
                   "applies/s is host-dispatch-bound; the structural "
                   "claim is the zero-new-compiles and two-gemm "
                   "apply-census columns, which are dispatch-rate-"
                   "independent." if platform == "cpu" else None),
        "ok": ok,
    }
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps({"out": out_path, "ok": ok,
                      "speedups": {r["op"]: round(r["speedup"], 2)
                                   for r in rows}}))
    return artifact


def bench_updates(sizes=(64, 128, 256, 512), ks=(1, 4, 16), nb=32,
                  iters=24, refactor_sample=6,
                  out_path="BENCH_UPDATE_r01.json"):
    """The round-20 incremental-maintenance A/B: serve ``iters``
    operand mutations from the RESIDENT factor through the update
    verb (rank-k Cholesky up/downdate sweeps, QR row append/delete —
    O(n²k) per mutation, zero compiles after warmup) vs paying what a
    caller without the verb pays today: a full evict+refactor of the
    committed operand per mutation (O(n³)).

    One row per (op, n, k). The refactor arm is measured on a bounded
    sample (``refactor_sample``) and extrapolated to a rate. The
    model-flops columns carry the crossover structurally: a rank-k
    update beats a refactor iff 2n²k < n³/3, so large k on small n
    honestly loses — that per-(op,n,k) crossover IS the claim, not a
    blanket speedup. Each row also measures replica-sync cost: one
    more mutation checkpointed as a blob-level sha256 DELTA against
    the pre-mutation base vs the full re-transfer. QR appends reuse
    the untouched base-factor blobs (delta strictly below full);
    Cholesky rewrites its whole L blob (whole-matrix blob granularity
    — delta ≈ full, labeled honestly). CPU wall times are smoke
    (PERF.md policy); the structural columns — zero refactors, zero
    new compiles, the sync-byte split — are the portable claim."""
    import shutil
    import tempfile

    import jax

    import slate_tpu as st
    from slate_tpu.obs import flops as _fl
    from slate_tpu.runtime import Session
    from slate_tpu.runtime.checkpoint import (save_session,
                                              save_session_delta)

    platform = jax.devices()[0].platform
    rng = np.random.default_rng(20)
    rows = []
    for op in ("chol", "qr"):
        for n in sizes:
            m = n if op == "chol" else n + nb
            # ONE session per (op, n): the factor program compiles
            # once and every rank bucket warms against the same
            # resident (a session per (op, n, k) would re-pay the
            # factor compile 3x and time nothing different)
            sess = Session(hbm_budget=1 << 30)
            # the A/B measures the pure update path: a budget
            # refactor mid-loop would time the OTHER arm, so the
            # accumulation budget moves out of the way (the
            # budget-due path has its own bench_gate'd exercise
            # in chaos_serve and the serve artifact section)
            sess.enable_numerics(update_budget=1e18,
                                 condest_on_factor=False,
                                 sample_fraction=0.0)
            if op == "chol":
                a = rng.standard_normal((n, n)).astype(np.float32)
                spd = (a @ a.T + n * np.eye(n)).astype(np.float32)
                A = st.hermitian(np.tril(spd), nb=nb,
                                 uplo=st.Uplo.Lower)
            else:
                dense = rng.standard_normal((m, n)) \
                    .astype(np.float32)
                A = st.from_dense(dense, nb=nb)
            h = sess.register(A, op=op)
            for k in ks:
                sess.warmup(h, nrhs=1, update_k=k)
            for k in ks:
                # pre-generate the mutation stream off the clock
                if op == "chol":
                    muts = [(1e-3 * rng.standard_normal((n, k)))
                            .astype(np.float32) for _ in range(iters)]
                else:
                    muts = [rng.standard_normal((k, n))
                            .astype(np.float32) for _ in range(iters)]
                nc0 = len(sess.compile_log)
                c0 = sess.metrics.snapshot()["counters"]
                mcur = m
                t0 = time.perf_counter()
                for i, w in enumerate(muts):
                    if op == "qr" and i % 2 == 1:
                        # delete the rows the previous iteration
                        # appended (keeps the resident bounded; the
                        # back-to-base slice is the cheap half of the
                        # serving mix, honestly in the mean)
                        sess.update(h, delete=list(
                            range(mcur - k, mcur)))
                        mcur -= k
                    else:
                        sess.update(h, w)
                        if op == "qr":
                            mcur += k
                update_wall = time.perf_counter() - t0
                c1 = sess.metrics.snapshot()["counters"]
                new_compiles = len(sess.compile_log) - nc0
                update_refactors = (
                    c1.get("update_refactors_total", 0)
                    - c0.get("update_refactors_total", 0))

                # refactor arm: the same mutated operand served the
                # pre-round-20 way — one full evict+factor per
                # mutation (the factor program is already warm)
                nref = min(iters, refactor_sample)
                t0 = time.perf_counter()
                for _ in range(nref):
                    sess.evict(h)
                    sess.factor(h)
                refactor_wall = time.perf_counter() - t0

                # replica-sync split: ONE more mutation, shipped as a
                # blob-level sha256 delta against the pre-mutation
                # base vs the full re-transfer
                bdir = tempfile.mkdtemp(prefix="slate_bench_upd_")
                ddir = tempfile.mkdtemp(prefix="slate_bench_upd_")
                try:
                    base_manifest = save_session(
                        sess, bdir, only=[h], host="bench")
                    sess.update(h, muts[0] if op == "chol"
                                else muts[0][:k])
                    _, stats = save_session_delta(
                        sess, ddir, base_manifest, only=[h],
                        host="bench")
                    if op == "qr":
                        # back to the base row count so the NEXT
                        # rank bucket's timed loop reuses its warmed
                        # base-shape programs
                        sess.update(h, delete=list(range(m, m + k)))
                finally:
                    shutil.rmtree(bdir, ignore_errors=True)
                    shutil.rmtree(ddir, ignore_errors=True)

                ups = iters / update_wall
                rps = nref / refactor_wall
                row = {
                    "op": op, "m": m, "n": n, "k": k, "nb": nb,
                    "update": {"wall_s": update_wall, "count": iters,
                               "updates_per_sec": ups},
                    "refactor": {"wall_s": refactor_wall,
                                 "sampled": nref,
                                 "refactors_per_sec": rps},
                    "speedup": ups / rps,
                    "model_flops": {
                        "update": _fl.update_flops(op, m, n, k),
                        "refactor": _fl.factor_flops(op, m, n),
                        # the per-(op,n,k) crossover, stated
                        # structurally: the incremental path wins
                        # iff its O(n²k) undercuts the O(n³)
                        # refactor — large k on small n honestly
                        # loses, and the committed artifact says so
                        "update_wins": _fl.update_flops(op, m, n, k)
                        < _fl.factor_flops(op, m, n),
                    },
                    "sync": {
                        "delta_bytes": stats["sync_bytes"],
                        "full_bytes": stats["full_bytes"],
                        "ratio": stats["sync_bytes"]
                        / max(stats["full_bytes"], 1),
                        "reused_blobs": stats["reused_blobs"],
                    },
                    "new_compiles_after_warmup": new_compiles,
                    "update_refactors": update_refactors,
                }
                row["ok"] = (
                    update_refactors == 0 and new_compiles == 0
                    and row["sync"]["delta_bytes"]
                    <= row["sync"]["full_bytes"]
                    and (op != "qr" or row["sync"]["delta_bytes"]
                         < row["sync"]["full_bytes"]))
                rows.append(row)
                print(f"# updates[{op} n={n} k={k}]: "
                      f"{ups:.1f} updates/s vs {rps:.1f} refactors/s "
                      f"-> {row['speedup']:.1f}x, delta "
                      f"{row['sync']['delta_bytes']}B vs full "
                      f"{row['sync']['full_bytes']}B "
                      f"(compiles after warmup: {new_compiles})",
                      file=sys.stderr)

    delta_total = sum(r["sync"]["delta_bytes"] for r in rows)
    full_total = sum(r["sync"]["full_bytes"] for r in rows)
    ok = (bool(rows) and all(r["ok"] for r in rows)
          and delta_total < full_total)
    artifact = {
        "bench": "serve_update",
        "platform": platform,
        "nb": nb, "iters": iters,
        "rows": rows,
        "sync_totals": {"delta_bytes": delta_total,
                        "full_bytes": full_total},
        "caveat": ("CPU smoke (TPU tunnel down since round 5): "
                   "updates/s and refactors/s are host-dispatch-"
                   "bound, so the wall-clock crossover shifts; the "
                   "structural claim is the zero-refactor/zero-"
                   "compile columns, the model-flops crossover "
                   "(2n²k vs n³/3), and the delta-vs-full sync-byte "
                   "split, which are dispatch-rate-independent."
                   if platform == "cpu" else None),
        "ok": ok,
    }
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps({"out": out_path, "ok": ok,
                      "sync_totals": artifact["sync_totals"],
                      "speedups": {f"{r['op']}/n{r['n']}/k{r['k']}":
                                   round(r["speedup"], 2)
                                   for r in rows}}))
    return artifact


def bench_tuned(sizes=(64, 128), nb=32, requests=32, dtype=np.float32,
                ops=("chol", "lu"), table=None,
                out_path="BENCH_TUNED_r01.json"):
    """Tuned-vs-default serving A/B (round 21): the same resident-
    factor serve through a default Session and through one constructed
    with the committed tuning table. One row per (op, n): both arms'
    solves/sec, both arms' compile counts (warmup compiles recorded,
    new-compiles-after-warmup exit-gated ZERO — the table must never
    put compilation back on the serve path), and the tuned arm's
    resolved config provenance. The throughput pair on CPU is smoke —
    dispatch-noise-dominated like every serve number this repo
    measures on a host CPU (the platform stamp keeps it informational
    in the gate); the structural columns are the portable claim."""
    import jax

    import slate_tpu as st
    from slate_tpu import tuning as tn

    platform = jax.devices()[0].platform
    from slate_tpu.runtime import Session

    table = tn.TuningTable.from_path() if table is None else table
    rng = np.random.default_rng(29)
    rows = []

    def _arm(sess, A, op, n, dense):
        h = sess.register(A, op=op)
        resolved = sess._ops[h].tuned
        sess.warmup(h)
        warm_compiles = len(sess.compile_log)
        nc0 = warm_compiles
        bs = [rng.standard_normal(n).astype(dtype)
              for _ in range(requests)]
        xs = []
        t0 = time.perf_counter()
        for b in bs:
            xs.append(sess.solve(h, b))
        wall = time.perf_counter() - t0
        new_compiles = len(sess.compile_log) - nc0
        xd = np.linalg.solve(dense.astype(np.float64),
                             bs[-1].astype(np.float64))
        rel = float(np.abs(np.asarray(xs[-1], np.float64).ravel()
                           - xd).max() / max(np.abs(xd).max(), 1.0))
        return {
            "solves_per_sec": requests / wall,
            "warmup_compiles": warm_compiles,
            "new_compiles_after_warmup": new_compiles,
            "config": resolved,
            "rel_err": rel,
        }

    for op in ops:
        for n in sizes:
            a = rng.standard_normal((n, n)).astype(dtype)
            if op == "chol":
                dense = a @ a.T + n * np.eye(n, dtype=dtype)
                A = st.hermitian(np.tril(dense), nb=nb,
                                 uplo=st.Uplo.Lower)
            else:
                dense = a + n * np.eye(n, dtype=dtype)
                A = st.from_dense(dense, nb=nb)
            # default arm FIRST: Session(tuning=...) activates the
            # process-global table, so the untuned measurement must
            # complete before the tuned session exists
            tn.activate_table(None)
            default = _arm(Session(), A, op, n, dense)
            tuned_sess = Session(tuning=table)
            try:
                tuned = _arm(tuned_sess, A, op, n, dense)
            finally:
                tn.activate_table(None)
            tol = 1e-3 if np.dtype(dtype).itemsize <= 4 else 1e-8
            rows.append({
                "op": op, "n": n, "dtype": np.dtype(dtype).name,
                "default": default, "tuned": tuned,
                "speedup": (tuned["solves_per_sec"]
                            / default["solves_per_sec"]),
                "ok": (default["new_compiles_after_warmup"] == 0
                       and tuned["new_compiles_after_warmup"] == 0
                       and default["rel_err"] < tol
                       and tuned["rel_err"] < tol),
            })
            print(f"# tuned A/B {op} n={n}: default "
                  f"{default['solves_per_sec']:.1f}/s vs tuned "
                  f"{tuned['solves_per_sec']:.1f}/s "
                  f"({rows[-1]['speedup']:.2f}x, "
                  f"config={tuned['config']})", file=sys.stderr)
    artifact = {
        "bench": "serve_tuned",
        "platform": platform,
        "dtype": np.dtype(dtype).name,
        "requests": requests,
        "table": {"file": tn.TUNING_FILENAME,
                  "schema": tn.TUNING_SCHEMA,
                  "entries": len(table.entries)},
        "rows": rows,
        "ok": all(r["ok"] for r in rows),
    }
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps({"bench": "serve_tuned", "platform": platform,
                      "rows": len(rows), "ok": artifact["ok"]},
                     sort_keys=True))
    return artifact


def _probe_device_count(timeout=90):
    """Default-backend device count, probed in a subprocess with a
    hard timeout — with the TPU tunnel down, jax.devices() hangs
    UNINTERRUPTIBLY in-process at backend init (the bench.py lesson),
    so the probe must run where it can be killed. Returns 0 on
    failure/timeout."""
    import subprocess

    code = "import jax; print(len(jax.devices()))"
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           timeout=timeout, capture_output=True,
                           text=True)
        if r.returncode == 0 and r.stdout.strip():
            return int(r.stdout.strip().splitlines()[-1])
    except Exception:
        pass
    return 0


def _reexec_multichip(argv, n_devices):
    """Re-exec under a forced n_devices virtual CPU mesh (the
    dryrun_multichip recipe: XLA_FLAGS must be final before any jax
    backend initializes, so the parent never imports jax)."""
    import os
    import subprocess

    env = dict(os.environ)
    env["_SLATE_TPU_MULTICHIP_CHILD"] = "1"
    flags = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    env["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    from slate_tpu.compat import platform as _platform
    if ("xla_cpu_collective_call_terminate_timeout_seconds"
            not in env["XLA_FLAGS"]):
        env["XLA_FLAGS"] += \
            _platform.collective_timeout_flag_if_supported()
    env["JAX_PLATFORMS"] = "cpu"
    # the f64 rows must really compute in f64: without x64 jax
    # silently downcasts and the "float64" arm is f32-accurate (the
    # dtype-aware residual bound catches exactly this)
    env["JAX_ENABLE_X64"] = "1"
    here = os.path.dirname(os.path.abspath(__file__))
    r = subprocess.run([sys.executable, os.path.abspath(__file__)]
                       + argv, env=env, cwd=here)
    return r.returncode


def bench_forecast(n=192, nb=64, requests=32, max_batch=8,
                   dtype=np.float32, cycles=6, period_s=300.0,
                   step_s=10.0, micro_samples=20000,
                   out_path="BENCH_FORECAST_r01.json"):
    """The round-23 sensing-substrate A/B (BENCH_FORECAST artifact):

    * ``serve``   — the same warmed resident-factor serve with the
      time-series store pumping FORCED on every result vs no store at
      all: the store's worst-case cost on the request path (the
      in-bench integration throttles to 4 Hz; this arm is the upper
      bound).
    * ``store``   — the record-path micro: ns per ``record_gauge``
      sample through ring + both downsample tiers, measured over
      ``micro_samples`` appends on one series.
    * ``holdout`` — predicted-vs-actual: a deterministic diurnal
      trace (fixed rng, injected clock), first ``cycles-1`` cycles
      shown to the forecaster, last cycle held out; MAE of the
      forecast over the held-out cycle vs the naive last-value
      baseline's MAE. The seasonal ladder must (a) find the true
      period, (b) beat naive, and (c) claim NO period on an aperiodic
      control trace — a forecaster that hallucinates seasonality
      would pre-warm the wrong handles on schedule.

    Exit: ok iff the holdout gates hold and both serve arms ran.
    Wall-clock numbers are honestly labeled CPU smoke when run there.
    """
    import jax

    import slate_tpu as st
    from slate_tpu.obs.forecast import forecast_points
    from slate_tpu.obs.timeseries import TimeseriesStore
    from slate_tpu.runtime import Executor, Session

    A, _spd = _build_operator(n, nb, dtype)
    rng = np.random.default_rng(11)
    rhs = [rng.standard_normal(n).astype(dtype)
           for _ in range(requests)]

    def _serve_arm(with_store):
        sess = Session(hbm_budget=1 << 30)
        if with_store:
            sess.enable_timeseries(interval_s=0.0)
        h = sess.register(A, op="chol")
        with Executor(sess, max_batch=max_batch, max_wait=1e-3) as ex:
            ex.warmup([h])
            t0 = time.perf_counter()
            futs = [ex.submit(h, b) for b in rhs]
            pumped = 0
            for f in futs:
                f.result(timeout=600)
                if with_store:
                    pumped += sess.pump_timeseries(force=True)
            wall = time.perf_counter() - t0
        return requests / wall, pumped, sess

    base_sps, _, _ = _serve_arm(False)
    store_sps, pumped, sess = _serve_arm(True)
    overhead_pct = 100.0 * (base_sps - store_sps) / base_sps

    # -- record-path micro (injected clock: no wall reads in the loop)
    mstore = TimeseriesStore(clock=lambda: 0.0)
    t0 = time.perf_counter()
    for i in range(micro_samples):
        mstore.record_gauge("micro", float(i & 1023), t=0.5 * i)
    record_ns = (time.perf_counter() - t0) / micro_samples * 1e9

    # -- holdout: seasonal trace, last cycle held out ----------------------
    hrng = np.random.default_rng(23)
    steps_per_cycle = int(period_s / step_s)
    total = steps_per_cycle * cycles
    ts0 = 1_000.0
    series = [(ts0 + step_s * i,
               5.0 + 3.0 * math.sin(2 * math.pi * i / steps_per_cycle)
               + float(hrng.normal(0.0, 0.15)))
              for i in range(total)]
    train = series[:-steps_per_cycle]
    test = dict((round(t, 6), v) for t, v in series[-steps_per_cycle:])
    fc = forecast_points(train, horizon_s=period_s)
    pairs = [(p[1], test[round(p[0], 6)]) for p in fc["points"]
             if round(p[0], 6) in test]
    mae = (sum(abs(a - b) for a, b in pairs) / len(pairs)
           if pairs else float("inf"))
    naive = train[-1][1]
    naive_mae = sum(abs(naive - v) for v in test.values()) / len(test)
    improvement = naive_mae / mae if mae > 0 else float("inf")

    # aperiodic control: drifting white noise must yield NO period
    arng = np.random.default_rng(29)
    ap = [(ts0 + step_s * i, 2.0 + 0.001 * i
           + float(arng.normal(0.0, 0.5))) for i in range(total)]
    ap_fc = forecast_points(ap[:-steps_per_cycle], horizon_s=period_s)

    holdout_ok = (fc["period_s"] == period_s
                  and fc["method"] in ("holt_winters",
                                       "seasonal_naive")
                  and improvement > 1.0
                  and ap_fc["period_s"] is None)
    artifact = {
        "bench": "serve_forecast",
        "platform": jax.devices()[0].platform,
        "dtype": np.dtype(dtype).name,
        "n": n, "nb": nb, "requests": requests,
        "note": "store overhead is the FORCED per-result pump (upper "
                "bound; the serve bench throttles to 4 Hz); wall "
                "numbers are CPU smoke unless platform says tpu",
        "serve": {
            "with_store_solves_per_sec": store_sps,
            "without_store_solves_per_sec": base_sps,
            "overhead_pct": overhead_pct,
            "samples_recorded": pumped,
            "series_count": sess.timeseries.payload()["series_count"],
        },
        "store": {
            "record_ns_per_sample": record_ns,
            "micro_samples": micro_samples,
        },
        "holdout": {
            "period_s_true": period_s,
            "period_s_detected": fc["period_s"],
            "method": fc["method"],
            "points_train": len(train),
            "points_test": len(test),
            "matched_points": len(pairs),
            "mae": mae,
            "naive_mae": naive_mae,
            "improvement": improvement,
            "aperiodic_period_s": ap_fc["period_s"],
            "aperiodic_method": ap_fc["method"],
        },
        "ok": bool(holdout_ok and base_sps > 0 and store_sps > 0
                   and pumped > 0),
    }
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps({"bench": "serve_forecast", "ok": artifact["ok"],
                      "overhead_pct": round(overhead_pct, 2),
                      "record_ns_per_sample": round(record_ns, 1),
                      "holdout_improvement": round(improvement, 2),
                      "method": fc["method"]}, sort_keys=True))
    return artifact


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--smoke", action="store_true",
                   help="small CPU run, <60 s; exit 0 iff serving beat "
                        "per-request factor+solve (and, with --batched, "
                        "iff the batched rows were written and "
                        "structurally one-program)")
    p.add_argument("--batched", action="store_true",
                   help="run the many-small-problems req/s A/B instead "
                        "of the resident-factor bench")
    p.add_argument("--multichip", action="store_true",
                   help="run the pod-scale serving A/B (mesh-sharded "
                        "resident factor vs single-device) and write "
                        "the structured MULTICHIP artifact; forces a "
                        "virtual 8-device CPU mesh when fewer devices "
                        "are visible")
    p.add_argument("--mixed", action="store_true",
                   help="run the mixed-precision serving A/B (refined-"
                        "from-low-precision resident vs full-precision "
                        "serve) and write the serve_mixed artifact; "
                        "exit 0 iff every row's structural columns "
                        "hold (half-byte residents, ~2x residents per "
                        "budget, zero fallbacks)")
    p.add_argument("--overload", action="store_true",
                   help="run the round-14 shedding A/B: the same 2x "
                        "sustained overload with and without admission "
                        "control + load shedding; exit 0 iff shedding "
                        "bounds p99/queue age while the no-shed arm's "
                        "grow (CPU smoke, honestly labeled)")
    p.add_argument("--overload-out", default="BENCH_OVERLOAD_r01.json")
    p.add_argument("--tenants-fair", action="store_true",
                   help="run the round-18 tenant-isolation A/B: the "
                        "same 2x overload (aggressor at 3x the victim's "
                        "rate) served FIFO/no-quotas vs weighted-fair + "
                        "quotas; exit 0 iff isolation bounds the victim "
                        "p99 and quota-rejects the aggressor's excess "
                        "while FIFO starves the victim (CPU smoke, "
                        "honestly labeled)")
    p.add_argument("--fair-out", default="BENCH_FAIR_r01.json")
    p.add_argument("--failover", action="store_true",
                   help="run the round-17 failover A/B: kill a fleet "
                        "member and recover with replication+checkpoint "
                        "vs cold refactor-on-miss; exit 0 iff the "
                        "protected arm recovers every affected handle "
                        "with zero refactors while the cold arm pays "
                        "one per handle (CPU smoke, honestly labeled)")
    p.add_argument("--failover-out", default="BENCH_FAILOVER_r01.json")
    p.add_argument("--spectral", action="store_true",
                   help="run the round-19 resident-spectral A/B: "
                        "theta-varying matrix-function applies from a "
                        "resident eigendecomposition vs the full "
                        "two-stage decomposition per request; exit 0 "
                        "iff every row is structurally one-program "
                        "(zero compiles after warmup, two-gemm apply "
                        "census) and the resident arm wins (CPU "
                        "smoke, honestly labeled)")
    p.add_argument("--spectral-out", default="BENCH_SPECTRAL_r01.json")
    p.add_argument("--updates", action="store_true",
                   help="run the round-20 incremental-maintenance "
                        "A/B: rank-k updates / QR row appends served "
                        "from the resident factor vs a full "
                        "evict+refactor per mutation, plus the "
                        "delta-vs-full replica-sync byte split; exit "
                        "0 iff every row is structurally clean (zero "
                        "refactors, zero compiles after warmup) and "
                        "delta sync undercuts full re-transfer (CPU "
                        "smoke, honestly labeled)")
    p.add_argument("--updates-out", default="BENCH_UPDATE_r01.json")
    p.add_argument("--tuned", action="store_true",
                   help="tuned-vs-default serving A/B (round 21): the "
                        "same resident-factor serve through a default "
                        "Session vs one built with the committed "
                        "TUNING_r01.json; writes one serve_tuned row "
                        "per (op, n) with both arms' solves/sec, "
                        "compile counts, and config provenance")
    p.add_argument("--tuned-out", default="BENCH_TUNED_r01.json")
    p.add_argument("--forecast", action="store_true",
                   help="run the round-23 sensing-substrate A/B: the "
                        "same warmed serve with the time-series store "
                        "pumping per-result vs without, the "
                        "record-path micro, and the predicted-vs-"
                        "actual holdout (seasonal trace, last cycle "
                        "held out, MAE vs naive-last); exit 0 iff the "
                        "forecaster finds the true period, beats "
                        "naive, and claims no period on the aperiodic "
                        "control (CPU smoke, honestly labeled)")
    p.add_argument("--forecast-out", default="BENCH_FORECAST_r01.json")
    p.add_argument("--regen-smoke", action="store_true",
                   help="GUARDED regeneration of the committed "
                        "BENCH_SERVE_smoke.json fixture (+ .metrics."
                        "json/.prom sidecars) in the repo root — run "
                        "after any artifact-schema change; plain "
                        "--smoke writes a /tmp throwaway so routine CI "
                        "runs can no longer silently rewrite (or "
                        "silently NOT rewrite) the committed fixture")
    p.add_argument("--mixed-out", default="BENCH_MIXED_r01.json")
    p.add_argument("--multichip-out", default="MULTICHIP_r06.json")
    p.add_argument("--devices", type=int, default=8,
                   help="device count for the forced multichip mesh")
    p.add_argument("--n", type=int, default=512)
    p.add_argument("--nb", type=int, default=128)
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--max-batch", type=int, default=16)
    p.add_argument("--out", default="BENCH_SERVE.json")
    p.add_argument("--batched-out", default="BENCH_r08.json")
    p.add_argument("--batch-sizes", type=int, nargs="+",
                   default=[100, 1000, 10000])
    p.add_argument("--sizes", type=int, nargs="+",
                   default=[32, 64, 128, 256])
    args = p.parse_args(argv)
    if args.tenants_fair:
        if args.smoke:
            art = bench_tenants_fair(n=32, nb=16, waves=3,
                                     out_path=args.fair_out)
        else:
            art = bench_tenants_fair(out_path=args.fair_out)
        return 0 if art["ok"] else 1
    if args.failover:
        if args.smoke:
            art = bench_failover(n=32, nb=16, n_handles=4,
                                 out_path=args.failover_out)
        else:
            art = bench_failover(out_path=args.failover_out)
        return 0 if art["ok"] else 1
    if args.spectral:
        if args.smoke:
            art = bench_spectral(n=64, nb=16, requests=16,
                                 cold_sample=4,
                                 out_path=args.spectral_out)
        else:
            art = bench_spectral(out_path=args.spectral_out)
        return 0 if art["ok"] else 1
    if args.updates:
        if args.smoke:
            art = bench_updates(sizes=(32, 48), ks=(1, 2), iters=8,
                                nb=16, refactor_sample=4,
                                out_path=args.updates_out)
        else:
            art = bench_updates(out_path=args.updates_out)
        return 0 if art["ok"] else 1
    if args.tuned:
        if args.smoke:
            art = bench_tuned(sizes=(48, 64), nb=16, requests=16,
                              out_path=args.tuned_out)
        else:
            art = bench_tuned(out_path=args.tuned_out)
        return 0 if art["ok"] else 1
    if args.forecast:
        if args.smoke:
            art = bench_forecast(n=96, nb=32, requests=16,
                                 max_batch=4, cycles=5,
                                 micro_samples=5000,
                                 out_path=args.forecast_out)
        else:
            art = bench_forecast(out_path=args.forecast_out)
        return 0 if art["ok"] else 1
    if args.overload:
        art = bench_overload(out_path=args.overload_out)
        return 0 if art["ok"] else 1
    if args.multichip:
        if "_SLATE_TPU_MULTICHIP_CHILD" not in os.environ \
                and _probe_device_count() < args.devices:
            # fewer real devices than the mesh needs (or a dead
            # backend): force the virtual CPU mesh in a re-exec'd
            # child — XLA_FLAGS must be final before jax initializes
            # a backend (the dryrun_multichip recipe). A host that
            # ALREADY sees enough devices (a real TPU slice) benches
            # them directly and the artifact's platform stamp makes
            # the rows gateable.
            return _reexec_multichip(
                sys.argv[1:] if argv is None else list(argv),
                args.devices)
        if args.smoke:
            art = bench_multichip(n=64, nb=16, requests=16, max_batch=4,
                                  dtypes=("float32",),
                                  n_devices=args.devices,
                                  out_path=args.multichip_out)
        else:
            art = bench_multichip(n_devices=args.devices,
                                  out_path=args.multichip_out)
        return 0 if art["ok"] else 1
    if args.mixed:
        if args.smoke:
            art = bench_mixed(sizes=(96,), nb=32, requests=10,
                              out_path=args.mixed_out)
        else:
            art = bench_mixed(out_path=args.mixed_out)
        return 0 if art["ok"] else 1
    if args.batched:
        if args.smoke:
            # CPU smoke: tiny stacks, exit on schema/structure only —
            # the throughput number is dispatch-noise on a host CPU
            rows = bench_batched(batch_sizes=(24, 100), sizes=(32, 48),
                                 per_request_cap=16,
                                 out_path=args.batched_out)
        else:
            rows = bench_batched(batch_sizes=tuple(args.batch_sizes),
                                 sizes=tuple(args.sizes),
                                 out_path=args.batched_out)
        ok = bool(rows) and all(r["hlo_one_program"] for r in rows)
        return 0 if ok else 1
    if args.regen_smoke:
        # the guarded fixture-regeneration path: smoke settings, the
        # COMMITTED path (repo root), sections asserted by bench()
        args.n, args.nb, args.requests = 192, 64, 48
        args.out = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_SERVE_smoke.json")
        print(f"# regenerating committed smoke fixture -> {args.out}",
              file=sys.stderr)
    elif args.smoke:
        args.n, args.nb, args.requests = 192, 64, 48
        # a throwaway: routine smoke runs must not touch the committed
        # fixture (regenerate it deliberately with --regen-smoke)
        args.out = (args.out if args.out != "BENCH_SERVE.json"
                    else "/tmp/BENCH_SERVE_smoke.json")
    art = bench(n=args.n, nb=args.nb, requests=args.requests,
                max_batch=args.max_batch, out_path=args.out)
    # round 15: the tenants section exit-gates too — a run whose
    # per-tenant ledger stopped summing to the globals is broken
    # round 16: the numerics section exit-gates too — a healthy
    # operand misclassified (or dead probe seams) is a broken monitor
    # round 19: the spectral section exit-gates too — a resident
    # eigendecomposition that recompiles per theta (or whose apply
    # stopped being two gemms) is a broken serving claim
    # round 20: the updates section exit-gates too — a resident that
    # pays a full refactor (or a recompile) per served mutation is a
    # broken incremental-maintenance claim
    # round 21: the tuning section exit-gates too — a committed table
    # that stops loading, resolving, or serving compile-free is a
    # broken tuning claim
    # round 22: the incidents section exit-gates too — a journal that
    # drifted from its counters (or a probe incident that fails its
    # own schema) is a broken black box
    # round 23: the forecast section exit-gates too — a store whose
    # counter deltas stopped summing to the live counters (or whose
    # payloads fail their own schemas) is a broken sensing substrate
    ok = (art["speedup"] > 1.0 and art["tenants"]["conservation_ok"]
          and art["numerics"]["ok"] and art["spectral"]["ok"]
          and art["updates"]["ok"] and art["tuning"]["ok"]
          and art["incidents"]["ok"] and art["forecast"]["ok"])
    print(f"serve {art['serve']['solves_per_sec']:.1f} solves/s vs "
          f"per-request {art['per_request']['solves_per_sec']:.1f} "
          f"solves/s -> speedup {art['speedup']:.2f}x "
          f"(hit-rate {art['serve']['cache_hit_rate']:.2f})",
          file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
