#!/usr/bin/env python
"""Serving benchmark: resident-factor cached solves vs per-request
factor+solve.

Drives the slate_tpu.runtime stack end to end — Session (HBM-budget
factor cache) + Executor (batching, AOT warmup) — against the naive
baseline every caller pays today: one full factor+solve per request.
The headline is the throughput ratio; the artifact also records the
serving percentiles and cache hit-rate the runtime's Metrics export.

Artifact schema (JSON, one object; see PERF.md "bench_serve artifact"):
  {"bench": "serve", "backend": ..., "dtype": ...,
   "n": int, "nb": int, "requests": int, "max_batch": int,
   "serve":       {"wall_s", "solves_per_sec", "p50_ms", "p99_ms",
                   "cache_hit_rate", "batches", "gflops"},
   "per_request": {"wall_s", "solves_per_sec"},
   "speedup": serve.solves_per_sec / per_request.solves_per_sec}

--smoke: small shapes on CPU, <60 s, exit 0 iff the artifact was
written and cached-factor serving beat per-request factor+solve
(speedup > 1) — wired into examples/run_tests.py.

--batched (round 10): the many-small-problems A/B — B independent
small systems served as ONE batched program (api.gesv_batched /
posv_batched through the pow2 batch-bucket engine) vs B per-request
programs (the same engine at B=1 per call). Emits one
``serve_batched`` row per (op, n, B) combo to ``--batched-out``
(BENCH_r08.json) — a JSON LIST that tools/bench_gate.py normalizes and
gates per (metric, platform, n, batch) series. The per-request arm is
measured on a bounded sample at large B (recorded in the row); the
throughput claim on CPU is SMOKE ONLY — in-op batch parallelism is a
TPU lowering property, backed structurally by the rows'
``hlo_one_program`` flag (no per-item factorization custom-call loop
in the batched program, same evidence class as rounds 6–7).
"""

import argparse
import json
import sys
import time

import numpy as np

from slate_tpu.compat.platform import apply_env_platforms

apply_env_platforms()


def _build_operator(n, nb, dtype):
    import slate_tpu as st

    rng = np.random.default_rng(7)
    a = rng.standard_normal((n, n)).astype(dtype)
    spd = a @ a.T + n * np.eye(n, dtype=dtype)
    A = st.hermitian(np.tril(spd), nb=nb, uplo=st.Uplo.Lower)
    return A, spd


def bench(n=512, nb=128, requests=64, max_batch=16, max_wait=1e-3,
          dtype=np.float32, out_path="BENCH_SERVE.json"):
    import jax

    import slate_tpu as st
    from slate_tpu.runtime import Executor, Session

    A, spd = _build_operator(n, nb, dtype)
    rng = np.random.default_rng(11)
    rhs = [rng.standard_normal(n).astype(dtype) for _ in range(requests)]

    # -- baseline: factor+solve per request (what callers pay today) ------
    def per_request_solve(b):
        X, info = st.posv(A, st.from_dense(b[:, None], nb=nb))
        return jax.block_until_ready(X.data)

    per_request_solve(rhs[0])  # warm the compile caches
    t0 = time.perf_counter()
    for b in rhs:
        per_request_solve(b)
    per_request_wall = time.perf_counter() - t0

    # -- serving runtime: resident factor + batched dispatch --------------
    sess = Session(hbm_budget=1 << 30)
    h = sess.register(A, op="chol")
    with Executor(sess, max_batch=max_batch, max_wait=max_wait) as ex:
        ex.warmup([h])  # factor + AOT compile off the request path
        t0 = time.perf_counter()
        futs = [ex.submit(h, b) for b in rhs]
        xs = [f.result(timeout=600) for f in futs]
        serve_wall = time.perf_counter() - t0

    # correctness spot check (serving a wrong answer fast is not a win)
    resid = max(float(np.abs(spd @ x - b).max()) / n
                for x, b in zip(xs[:4], rhs[:4]))
    if not resid < 1e-2:
        raise RuntimeError(f"serving residual too large: {resid}")

    snap = sess.metrics.snapshot()
    lat = snap["histograms"].get("request_latency", {})
    artifact = {
        "bench": "serve",
        "backend": jax.devices()[0].platform,
        "dtype": np.dtype(dtype).name,
        "n": n, "nb": nb, "requests": requests, "max_batch": max_batch,
        "serve": {
            "wall_s": serve_wall,
            "solves_per_sec": requests / serve_wall,
            "p50_ms": lat.get("p50", 0.0) * 1e3,
            "p99_ms": lat.get("p99", 0.0) * 1e3,
            "cache_hit_rate": snap["derived"]["cache_hit_rate"],
            "batches": snap["counters"].get("batches_total", 0),
            "gflops": snap["derived"]["gflops"],
        },
        "per_request": {
            "wall_s": per_request_wall,
            "solves_per_sec": requests / per_request_wall,
        },
        # round 9: per-shape cost rows harvested at the AOT seam (model
        # flops, XLA bytes-accessed, arg/out/temp/peak HBM, collective
        # census) and the session's point-in-time HBM gauges
        "cost_log": sess.cost_log,
        "hbm": snap.get("gauges", {}),
    }
    artifact["speedup"] = (artifact["serve"]["solves_per_sec"]
                           / artifact["per_request"]["solves_per_sec"])
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")
    # exposition-format fixtures alongside the headline artifact
    # (ISSUE 4): the full Metrics snapshot as JSON and the Prometheus
    # text rendering a fleet scraper would pull from /metrics — so a
    # BENCH_SERVE run doubles as a committed example of both formats
    stem = out_path[:-5] if out_path.endswith(".json") else out_path
    sess.metrics.to_json(stem + ".metrics.json")
    from slate_tpu.obs import render_prometheus
    with open(stem + ".prom", "w") as f:
        f.write(render_prometheus(snap))
    print(f"# metrics snapshot -> {stem}.metrics.json, prometheus text "
          f"-> {stem}.prom", file=sys.stderr)
    print(json.dumps(artifact, sort_keys=True))
    return artifact


def _hlo_one_program(name: str, batch: int, n: int) -> bool:
    """Structural evidence for one row: THIS row's bucket program's
    optimized HLO carries NO per-item factorization custom call (a
    vmap of lax.linalg custom calls would — the lowering class round 7
    measured 6× slower). Filtered to the row's (pow2 batch, n) program
    so one offending shape can't taint every other row's flag."""
    import re as _re

    from slate_tpu.linalg import batched as lb

    texts = lb.bucket_hlo(name, batch=batch, n=n)
    if not texts:
        return False
    pat = _re.compile(r"custom-call.*(getrf|potrf|geqrf|lu|cholesky)",
                      _re.IGNORECASE)
    return not any(pat.search(t) for t in texts)


def bench_batched(batch_sizes=(100, 1000, 10000), sizes=(32, 64, 128, 256),
                  ops=("gesv", "posv"), dtype=np.float32,
                  per_request_cap=64, mem_cap_bytes=1 << 30,
                  out_path="BENCH_r08.json"):
    """Req/s A/B per (op, n, B): ONE batched program vs B per-request
    (B=1) programs, both through the pow2-bucket engine, both warmed
    (compilation excluded — the bucket cache makes it a one-time cost
    per (op, n, nb, dtype, pow2-B)). Writes a JSON list of
    ``serve_batched`` rows."""
    import jax

    import slate_tpu as st
    from slate_tpu.linalg import batched as lb

    platform = jax.devices()[0].platform
    rng = np.random.default_rng(23)
    rows = []
    for n in sizes:
        for bsz in batch_sizes:
            itemsize = np.dtype(dtype).itemsize
            need = lb.batch_bucket(bsz) * n * n * itemsize * 4
            if need > mem_cap_bytes:
                print(f"# skip n={n} B={bsz}: ~{need >> 20} MiB stacked "
                      f"operands over the {mem_cap_bytes >> 20} MiB cap",
                      file=sys.stderr)
                continue
            base = rng.standard_normal((bsz, n, n)).astype(dtype)
            rhs = rng.standard_normal((bsz, n, 2)).astype(dtype)
            for op in ops:
                if op == "posv":
                    a = (base @ np.swapaxes(base, 1, 2)
                         + n * np.eye(n, dtype=dtype))
                    fn = st.posv_batched
                else:
                    a = base
                    fn = st.gesv_batched
                # warm both program buckets (pow2-B and B=1)
                jax.block_until_ready(fn(a, rhs)[0])
                jax.block_until_ready(fn(a[:1], rhs[:1])[0])
                t0 = time.perf_counter()
                x, info = fn(a, rhs)
                jax.block_until_ready(x)
                batched_wall = time.perf_counter() - t0
                # per-request arm: bounded sample, same engine at B=1
                m = min(bsz, per_request_cap)
                t0 = time.perf_counter()
                for i in range(m):
                    xi, _ = fn(a[i:i + 1], rhs[i:i + 1])
                jax.block_until_ready(xi)
                per_req_wall = (time.perf_counter() - t0) * (bsz / m)
                row = {
                    "bench": "serve_batched", "platform": platform,
                    "dtype": np.dtype(dtype).name, "op": op,
                    "n": n, "batch": bsz,
                    "bucket": lb.batch_bucket(bsz),
                    "batched": {
                        "wall_s": batched_wall,
                        "reqs_per_sec": bsz / batched_wall,
                    },
                    "per_request": {
                        "wall_s": per_req_wall,
                        "reqs_per_sec": bsz / per_req_wall,
                        "sampled": m,
                    },
                    "speedup": per_req_wall / batched_wall,
                    "hlo_one_program": _hlo_one_program(
                        f"{op}_batched", lb.batch_bucket(bsz), n),
                }
                rows.append(row)
                print(f"# {op} n={n} B={bsz}: batched "
                      f"{row['batched']['reqs_per_sec']:.0f} req/s vs "
                      f"per-request "
                      f"{row['per_request']['reqs_per_sec']:.0f} req/s "
                      f"({row['speedup']:.2f}x, "
                      f"one-program={row['hlo_one_program']})",
                      file=sys.stderr)
    with open(out_path, "w") as f:
        json.dump(rows, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps({"rows": len(rows), "out": out_path,
                      "platform": platform}))
    return rows


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--smoke", action="store_true",
                   help="small CPU run, <60 s; exit 0 iff serving beat "
                        "per-request factor+solve (and, with --batched, "
                        "iff the batched rows were written and "
                        "structurally one-program)")
    p.add_argument("--batched", action="store_true",
                   help="run the many-small-problems req/s A/B instead "
                        "of the resident-factor bench")
    p.add_argument("--n", type=int, default=512)
    p.add_argument("--nb", type=int, default=128)
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--max-batch", type=int, default=16)
    p.add_argument("--out", default="BENCH_SERVE.json")
    p.add_argument("--batched-out", default="BENCH_r08.json")
    p.add_argument("--batch-sizes", type=int, nargs="+",
                   default=[100, 1000, 10000])
    p.add_argument("--sizes", type=int, nargs="+",
                   default=[32, 64, 128, 256])
    args = p.parse_args(argv)
    if args.batched:
        if args.smoke:
            # CPU smoke: tiny stacks, exit on schema/structure only —
            # the throughput number is dispatch-noise on a host CPU
            rows = bench_batched(batch_sizes=(24, 100), sizes=(32, 48),
                                 per_request_cap=16,
                                 out_path=args.batched_out)
        else:
            rows = bench_batched(batch_sizes=tuple(args.batch_sizes),
                                 sizes=tuple(args.sizes),
                                 out_path=args.batched_out)
        ok = bool(rows) and all(r["hlo_one_program"] for r in rows)
        return 0 if ok else 1
    if args.smoke:
        args.n, args.nb, args.requests = 192, 64, 48
        args.out = (args.out if args.out != "BENCH_SERVE.json"
                    else "BENCH_SERVE_smoke.json")
    art = bench(n=args.n, nb=args.nb, requests=args.requests,
                max_batch=args.max_batch, out_path=args.out)
    ok = art["speedup"] > 1.0
    print(f"serve {art['serve']['solves_per_sec']:.1f} solves/s vs "
          f"per-request {art['per_request']['solves_per_sec']:.1f} "
          f"solves/s -> speedup {art['speedup']:.2f}x "
          f"(hit-rate {art['serve']['cache_hit_rate']:.2f})",
          file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
